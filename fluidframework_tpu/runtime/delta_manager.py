"""DeltaManager — the loader-layer transport engine for one container.

Reference parity: packages/loader/container-loader/src/deltaManager.ts:147
(inbound/outbound/inboundSignal DeltaQueues :197-199, sequence-gap detection
+ fetchMissingDeltas :1298-1360, connect/disconnect lifecycle :566-692,
readonly mode) — reshaped for a synchronous in-proc client: queues drain
eagerly on the pushing thread; pausing is the deterministic-interleaving
primitive tests use (test-utils OpProcessingController).

Gap handling: the live stream may skip sequence numbers (dropped socket
messages, reconnect races). Out-of-order arrivals park in ``_parked`` and a
catch-up read from delta storage fills the hole; duplicates (seq already
queued) drop silently. ``DataCorruptionError`` fires when the same seq
arrives twice with different payloads (deltaManager.ts:1336-1346).
"""

from __future__ import annotations

from typing import Any, Callable

from ..drivers.base import DocumentService
from ..protocol.messages import (
    DocumentMessage,
    MessageType,
    SequencedDocumentMessage,
)
from .delta_queue import DeltaQueue, DeltaScheduler


class DataCorruptionError(Exception):
    """Same sequence number delivered twice with different payloads."""


class FlushMode:
    """Outbound batching mode (containerRuntime.ts FlushMode)."""

    IMMEDIATE = "immediate"  # every submit flushes (reference Automatic)
    MANUAL = "manual"        # accumulate until flush() (orderSequentially)


class DeltaManager:
    """Inbound/outbound op pump between a driver connection and a handler."""

    #: Own echoed-but-not-proven-durable ops retained for reconnect
    #: resubmission. Bounded: the crash race lives at the stream tip (the
    #: per-op path journals before broadcasting; the storm path fsyncs
    #: before acking), so ops far behind the tip are durable in every
    #: non-pathological run — the oldest entry drops when the window
    #: overflows rather than growing with session length.
    RESUBMIT_WINDOW = 1024

    def __init__(
        self,
        service: DocumentService,
        process_message: Callable[[SequencedDocumentMessage], None],
        process_signal: Callable[[Any], None] | None = None,
        on_nack: Callable[[Any], None] | None = None,
        on_lost_ops: Callable[[list[SequencedDocumentMessage]], None]
        | None = None,
    ) -> None:
        self._service = service
        self._connection: Any = None
        self.client_id: str | None = None
        self.client_seq = 0
        self.last_processed_seq = 0   # seq of last message run through handler
        self.last_queued_seq = 0      # seq of last message accepted inbound
        # Acknowledged-durability watermark: the highest SEQUENCE NUMBER
        # the service has proven durable. Everything read back from delta
        # storage is durable by definition (it came from the journal).
        # NOTE the storm ack's "dw" field is a TICK-count watermark, not
        # a seq — a storm-aware host must feed note_durable with the
        # ack's per-doc last_seq once "dw" covers the tick, never "dw"
        # itself. A live broadcast above the watermark may still be lost
        # to a server crash — which is why own echoed ops stay in
        # _undurable until the watermark passes them.
        self.last_durable_seq = 0
        # Own ops echoed back (acked) but not yet known durable, oldest
        # first: the resubmit-on-reconnect candidates after a server
        # crash loses acked-but-unfsynced ops.
        self._undurable_own: list[SequencedDocumentMessage] = []
        self._on_lost_ops = on_lost_ops
        self.flush_mode = FlushMode.IMMEDIATE
        self._parked: dict[int, SequencedDocumentMessage] = {}
        self._fetching = False
        self._read_mode = False

        # Long inbound catch-ups yield through the scheduler
        # (deltaScheduler.ts:25): hosts register on_yield callbacks.
        self.scheduler = DeltaScheduler()
        self.inbound: DeltaQueue[SequencedDocumentMessage] = DeltaQueue(
            self._process_inbound, scheduler=self.scheduler)
        self.outbound: DeltaQueue[list[DocumentMessage]] = DeltaQueue(
            self._send_batch)
        self.inbound_signal: DeltaQueue[Any] = DeltaQueue(
            process_signal if process_signal is not None else lambda _s: None)
        self._process_message = process_message
        self._on_nack_cb = on_nack
        self._batch: list[DocumentMessage] = []

    # -- connection lifecycle --------------------------------------------------

    @property
    def connected(self) -> bool:
        return self._connection is not None

    @property
    def readonly(self) -> bool:
        """True in read mode AND while the connection is down — readonly
        degradation is the offline contract (deltaManager.ts readonly):
        a disconnected container reads its local state but cannot claim
        client seqs until the transport is back."""
        return self._read_mode or self._connection is None

    def handle_connection_lost(self) -> None:
        """Transport-level disconnect (dead socket, server kill): degrade
        to disconnected/readonly WITHOUT the disconnect RPC — there is no
        live socket to send it on. Pending local ops stay stashed for the
        post-reconnect replay; own echoed-but-unproven ops stay in the
        resubmit ring (the durability-watermark probe on the next
        connect() decides what the crashed server lost)."""
        if self._connection is None:
            return
        self._connection.open = False  # poison further submits locally
        self._teardown_session()

    def catch_up_to(self, to_seq: int) -> None:
        """Process stored deltas up to ``to_seq`` while still offline —
        the staging step of offline-resume: stashed ops re-apply at their
        original reference point, between this and connect()."""
        assert self._connection is None, "already connected"
        for message in self._service.delta_storage.get_deltas(
                self.last_queued_seq, to_seq):
            self.note_durable(message.sequence_number)
            self._accept(message)
        self.inbound.resume()  # drain exactly what was accepted
        self.inbound.pause()

    def note_durable(self, seq: int) -> None:
        """Advance the acknowledged-durability watermark (storage reads
        and service "dw" acks both feed this) and retire own echoed ops
        the service has now proven durable."""
        if seq <= self.last_durable_seq:
            return
        self.last_durable_seq = seq
        while (self._undurable_own
               and self._undurable_own[0].sequence_number <= seq):
            self._undurable_own.pop(0)

    def _check_lost_ops(self) -> None:
        """Resubmit-on-reconnect against the durability watermark: own
        ops that were ECHOED (acked) but never proven durable may have
        died with the server. Probe storage for them; any op the
        recovered journal does not hold is lost — hand it to the
        ``on_lost_ops`` hook (the runtime resubmits through its own
        channels, regenerating refs/clientSeqs) rather than silently
        converging without it."""
        if not self._undurable_own:
            return
        lo = self._undurable_own[0].sequence_number - 1
        hi = self._undurable_own[-1].sequence_number
        fetched = self._service.delta_storage.get_deltas(lo, hi)
        # Identity match, NOT bare sequence number: a recovered server
        # resumes numbering from its durable tip, so a seq our lost op
        # once held may now belong to ANOTHER client's post-crash
        # submission — which must not mask the loss.
        held = {(m.client_id, m.client_sequence_number) for m in fetched}
        lost = [m for m in self._undurable_own
                if (m.client_id, m.client_sequence_number) not in held]
        self._undurable_own = []
        if fetched:
            # The journal is seq-contiguous: holding N proves 1..N.
            self.last_durable_seq = max(self.last_durable_seq,
                                        fetched[-1].sequence_number)
        if lost and self._on_lost_ops is not None:
            self._on_lost_ops(lost)

    def connect(self, mode: str = "write") -> str:
        """Catch up from delta storage, then go live. Returns the client id.

        Catch-up ops and the live stream both land in the (paused) inbound
        queue in seq order; overlap dedupes by sequence number.
        """
        assert self._connection is None, "already connected"
        self._read_mode = mode == "read"
        self._check_lost_ops()
        for message in self._service.delta_storage.get_deltas(
                self.last_queued_seq):
            self.note_durable(message.sequence_number)
            self._accept(message)
        connection = self._service.connect(
            self._enqueue_messages,
            on_nack=self._handle_nack,
            on_signal=self.inbound_signal.push,
            mode=mode,
        )
        self._connection = connection
        self.client_id = connection.client_id
        self.client_seq = 0
        self.inbound.resume()
        self.outbound.resume()
        self.inbound_signal.resume()
        return connection.client_id

    def disconnect(self) -> None:
        if self._connection is None:
            return
        self._connection.close()
        self._teardown_session()

    def _teardown_session(self) -> None:
        """Shared tail of disconnect()/handle_connection_lost(): forget
        the connection and park the queues (the two paths differ only in
        whether the transport could carry a goodbye)."""
        self._connection = None
        self.client_id = None
        self._batch = []
        self.outbound.clear()  # stale clientSeqs; pending ops resubmit fresh
        self.inbound.pause()
        self.outbound.pause()
        self.inbound_signal.pause()

    # -- inbound: dedupe, order, gap-fetch -------------------------------------

    def _enqueue_messages(self,
                          messages: list[SequencedDocumentMessage]) -> None:
        for message in messages:
            self._accept(message)
        if self._parked and not self._fetching:
            self._fetch_missing()

    def _accept(self, message: SequencedDocumentMessage) -> None:
        seq = message.sequence_number
        if seq <= self.last_queued_seq:
            return  # duplicate from catch-up overlap / rebroadcast
        if seq == self.last_queued_seq + 1:
            self.last_queued_seq = seq
            self.inbound.push(message)
            # Unpark any directly-following messages.
            while self.last_queued_seq + 1 in self._parked:
                nxt = self._parked.pop(self.last_queued_seq + 1)
                self.last_queued_seq = nxt.sequence_number
                self.inbound.push(nxt)
            return
        # Gap: park and (re)fetch the hole from durable storage.
        parked = self._parked.get(seq)
        if parked is not None and parked != message:
            raise DataCorruptionError(
                f"two different messages for seq {seq}")
        self._parked[seq] = message

    def _fetch_missing(self) -> None:
        """Read the hole [last_queued+1, first_parked) from delta storage
        (deltaManager.ts fetchMissingDeltas → enqueueMessages)."""
        self._fetching = True
        try:
            while self._parked:
                first_parked = min(self._parked)
                if first_parked <= self.last_queued_seq + 1:
                    # Hole already closed by unparking.
                    while self.last_queued_seq + 1 in self._parked:
                        nxt = self._parked.pop(self.last_queued_seq + 1)
                        self.last_queued_seq = nxt.sequence_number
                        self.inbound.push(nxt)
                    # Drop any parked duplicates below the watermark.
                    for seq in [s for s in self._parked
                                if s <= self.last_queued_seq]:
                        del self._parked[seq]
                    continue
                fetched = self._service.delta_storage.get_deltas(
                    self.last_queued_seq, first_parked - 1)
                progressed = False
                for message in fetched:
                    before = self.last_queued_seq
                    self._accept(message)
                    progressed |= self.last_queued_seq > before
                if not progressed:
                    # Storage doesn't have the hole yet (broadcast raced the
                    # durable write); leave messages parked — the next
                    # delivery retries the fetch.
                    return
        finally:
            self._fetching = False

    def _process_inbound(self, message: SequencedDocumentMessage) -> None:
        if message.sequence_number <= self.last_processed_seq:
            return
        assert message.sequence_number == self.last_processed_seq + 1, (
            f"inbound queue disorder: got {message.sequence_number}, "
            f"expected {self.last_processed_seq + 1}")
        self.last_processed_seq = message.sequence_number
        if (message.client_id is not None
                and message.client_id == self.client_id
                and message.type == MessageType.OPERATION
                and message.sequence_number > self.last_durable_seq):
            # Own op echoed from a LIVE broadcast: acked, but the service
            # has not yet proven it durable — keep it resubmittable until
            # the watermark passes it (see _check_lost_ops).
            if len(self._undurable_own) >= self.RESUBMIT_WINDOW:
                self._undurable_own.pop(0)
            self._undurable_own.append(message)
        self._process_message(message)

    def _handle_nack(self, nack: Any) -> None:
        if self._on_nack_cb is not None:
            self._on_nack_cb(nack)

    # -- outbound --------------------------------------------------------------

    def allocate_client_seq(self) -> int | None:
        """Claim the next clientSequenceNumber, or None when disconnected.
        Callers record pending state against it BEFORE submit — the ack may
        arrive re-entrantly during the send (in-proc server)."""
        if self._connection is None or self._read_mode:
            return None
        self.client_seq += 1
        return self.client_seq

    def submit(self, mtype: MessageType, contents: Any,
               client_seq: int) -> None:
        assert not self._read_mode, "submit on a read-only connection"
        message = DocumentMessage(
            client_sequence_number=client_seq,
            reference_sequence_number=self.last_processed_seq,
            type=mtype,
            contents=contents,
        )
        self._batch.append(message)
        if self.flush_mode == FlushMode.IMMEDIATE:
            self.flush()

    def flush(self) -> None:
        if not self._batch:
            return
        batch, self._batch = self._batch, []
        self.outbound.push(batch)

    def _send_batch(self, batch: list[DocumentMessage]) -> None:
        assert self._connection is not None, "outbound drain while disconnected"
        self._connection.submit(batch)

    def submit_signal(self, content: Any) -> None:
        assert self._connection is not None, "signal while disconnected"
        self._connection.signal(content)


class AutoReconnector:
    """Automatic reconnect with exponential backoff + jitter for a
    DeltaManager over a re-dialable transport (drivers exposing
    ``reconnect()``, e.g. NetworkDocumentService).

    On the service's "disconnect" event the DeltaManager degrades to
    disconnected/readonly immediately (handle_connection_lost), then the
    retry loop re-dials on a
    :class:`~fluidframework_tpu.drivers.utils.ReconnectPolicy` schedule —
    honoring server ``retry_after_s`` hints from busy-nacks, so a
    reconnect storm self-spreads under the admission limit instead of
    hammering the front door. A successful connect() runs the usual
    catch-up + durability-watermark probe, then ``on_reconnected`` (the
    container replays pending ops there).

    ``spawn_thread=False`` leaves the loop to the caller (deterministic
    tests / simulations drive :meth:`run` with a fake sleep).
    """

    def __init__(self, delta_manager: DeltaManager, service,
                 policy=None, mode: str = "write",
                 max_attempts: int = 64,
                 sleep=None,
                 on_reconnected: Callable[[str], None] | None = None,
                 on_gave_up: Callable[[], None] | None = None,
                 spawn_thread: bool = True) -> None:
        import time

        from ..drivers.utils import ReconnectPolicy
        self.delta_manager = delta_manager
        self.service = service
        self.policy = policy if policy is not None else ReconnectPolicy()
        self.mode = mode
        self.max_attempts = max_attempts
        self.on_reconnected = on_reconnected
        # Fired (once per exhausted loop) when max_attempts runs out —
        # in spawned-thread mode the ConnectionError below dies with the
        # daemon thread, so this hook (plus the `gave_up` flag) is the
        # application's only signal that redialing was abandoned.
        self.on_gave_up = on_gave_up
        self.gave_up = False
        self._sleep = sleep if sleep is not None else time.sleep
        self._spawn_thread = spawn_thread
        # One redial loop at a time: a disconnect fired DURING a redial
        # (the fresh socket dying mid-connect) must not start a second
        # loop racing the first through the driver's reconnect().
        import threading
        self._run_guard = threading.Lock()
        # Set by every disconnect, cleared when a redial loop takes over:
        # a disconnect landing in the tail of a finishing run() (after
        # its connect succeeded, before the guard released) must not be
        # dropped — the finishing loop re-spawns if this is still set.
        self._redial_needed = False
        self.disconnects = 0
        self.attempts = 0  # attempts spent on the LAST successful redial
        service.events.on("disconnect", self._on_disconnect)

    def _on_disconnect(self) -> None:
        # Runs on the driver's dispatcher thread (holding dispatch_lock):
        # degrade NOW, retry elsewhere — the redial loop does RPCs that
        # need this thread free.
        self.delta_manager.handle_connection_lost()
        self.disconnects += 1
        self._redial_needed = True
        self._maybe_spawn()

    def _maybe_spawn(self) -> None:
        if self._spawn_thread and not self._run_guard.locked():
            import threading
            threading.Thread(target=self.run, daemon=True).start()

    def run(self) -> str | None:
        """The redial loop; returns the new client id, None when another
        loop already holds the redial (it will finish the job) or the
        connection is already back, or raises after ``max_attempts``.
        Connection refusals retry; throttling nacks retry after honoring
        the server's hint; non-retriable driver errors (auth)
        propagate."""
        from ..drivers.utils import DriverError
        if not self._run_guard.acquire(blocking=False):
            return None  # a concurrent loop is already redialing
        try:
            self._redial_needed = False
            if self.delta_manager.connected:
                return self.delta_manager.client_id  # nothing to redial
            retry_hint: float | None = None
            for attempt in range(self.max_attempts):
                self._sleep(self.policy.next_delay(attempt, retry_hint))
                retry_hint = None
                try:
                    # Re-dial only a DEAD transport: a connect refused by
                    # admission (throttled) arrives over a healthy fresh
                    # socket — tearing it down per retry would multiply
                    # front-door handshake churn by the attempt count,
                    # the very load the admission ladder bounds.
                    if getattr(self.service, "closed", True):
                        self.service.reconnect()
                    client_id = self.delta_manager.connect(self.mode)
                except DriverError as err:
                    if not err.can_retry:
                        # Auth-class failure: redialing cannot help. In
                        # spawned-thread mode the raise dies with the
                        # daemon thread, so signal abandonment first.
                        self.gave_up = True
                        if self.on_gave_up is not None:
                            self.on_gave_up()
                        raise
                    retry_hint = err.retry_after_s
                    continue
                except (ConnectionError, OSError):
                    continue  # server still down; back off further
                self.attempts = attempt + 1
                self.gave_up = False
                if self.on_reconnected is not None:
                    self.on_reconnected(client_id)
                return client_id
            self.gave_up = True
            if self.on_gave_up is not None:
                self.on_gave_up()
            raise ConnectionError(
                f"reconnect gave up after {self.max_attempts} attempts")
        finally:
            self._run_guard.release()
            if self._redial_needed:
                # A disconnect raced the tail of this loop: pick it up.
                self._maybe_spawn()
