"""Summary subsystem — elected client checkpoints the document.

Reference parity: packages/runtime/container-runtime/src/summarizer.ts +
summaryManager.ts (§3.5 of SURVEY.md): the oldest eligible quorum member is
elected to summarize; heuristics (ops-since-last-ack, injectable clock for
idle/max-time) decide when; generation = build full summary at the current
sequence number → upload to storage → submit a sequenced SUMMARIZE op
carrying the storage handle → service scribe validates, makes it
load-visible and sequences SUMMARY_ACK / SUMMARY_NACK.

Simplification vs the reference: the elected container summarizes over its
own connection instead of spawning a hidden "/_summarizer" client — the
in-proc client is synchronous, so the summary is generated at a quiesced
point (inside op processing) exactly as the reference's paused-inbound
summarizer does. The election + heuristics + ack protocol are the same.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable

from ..protocol.messages import (
    MessageType,
    ScopeType,
    SequencedDocumentMessage,
)

if TYPE_CHECKING:
    from .container import Container


@dataclass
class SummaryConfig:
    """When to summarize (reference ISummaryConfiguration heuristics)."""

    max_ops: int = 100           # ops since last ack before summarizing
    max_time_ms: float | None = None  # wall-time trigger (needs clock)
    min_ops_for_last_summary: int = 1  # don't summarize empty diffs
    # Give up waiting for an ack after this many further sequenced ops and
    # allow a fresh attempt (reference maxAckWaitTime, op-counted here).
    max_ack_wait_ops: int = 200


@dataclass
class SummarizerEvent:
    kind: str  # "generated" | "acked" | "nacked"
    sequence_number: int
    handle: str | None = None
    reason: str | None = None


class SummaryManager:
    """Per-container election + heuristics driver.

    Every client runs one; only the elected client acts. Election is the
    oldest eligible quorum member (lowest join sequence number) holding the
    summary-write scope — deterministic on the identical quorum state every
    replica maintains (summaryManager.ts oldest-client heuristic).
    """

    def __init__(self, container: "Container",
                 config: SummaryConfig | None = None,
                 clock: Callable[[], float] | None = None) -> None:
        self.container = container
        self.config = config or SummaryConfig()
        self.clock = clock
        self.ops_since_ack = 0
        self.last_ack_seq = 0
        self.last_summary_time = clock() if clock else 0.0
        self.pending_handle: str | None = None
        self.pending_since_seq = 0
        # Incremental-summary parent: the last ACKED summary's handle and
        # its GENERATION seq (the state it captured). Channels unchanged
        # since then serialize as handle stubs into it (summary.ts:53);
        # None gen seq = next summary is full (e.g. a peer's summary was
        # acked — we don't know what state it captured).
        self.last_acked_handle: str | None = None
        self.last_acked_gen_seq: int | None = None
        self.pending_gen_seq: int | None = None
        self.events: list[SummarizerEvent] = []
        self.enabled = True
        container.on_op_processed.append(self._on_op)
        container.on_nack.append(self._on_transport_nack)

    # -- election --------------------------------------------------------------

    def elected_client_id(self) -> str | None:
        """The quorum's oldest member with summary scope, or None."""
        members = self.container.protocol.quorum.get_members()
        best_id, best_seq = None, None
        for client_id, member in members.items():
            scopes = getattr(member.detail, "scopes", ())
            if ScopeType.SUMMARY_WRITE not in scopes:
                continue
            if best_seq is None or member.sequence_number < best_seq:
                best_id, best_seq = client_id, member.sequence_number
        return best_id

    @property
    def is_elected(self) -> bool:
        client_id = self.container.client_id
        return client_id is not None and client_id == self.elected_client_id()

    # -- heuristics ------------------------------------------------------------

    def _on_op(self, message: SequencedDocumentMessage) -> None:
        if message.type == MessageType.SUMMARY_ACK:
            self._on_ack(message)
            return
        if message.type == MessageType.SUMMARY_NACK:
            self._on_nack(message)
            return
        if message.type == MessageType.OPERATION:
            self.ops_since_ack += 1
        if self.pending_handle is not None and (
                message.sequence_number - self.pending_since_seq
                > self.config.max_ack_wait_ops):
            # The offer (or its ack) was lost in transit; stop waiting.
            self.pending_handle = None
        if not self.enabled or self.pending_handle is not None:
            return
        if not self.is_elected:
            return
        if self.container.runtime.pending.has_pending:
            # Local ops are optimistically applied but not yet sequenced: a
            # summary now would bake their effects in below their eventual
            # seq and double-apply them on load. Retry once acks drain.
            return
        if self.ops_since_ack < self.config.min_ops_for_last_summary:
            return
        due = self.ops_since_ack >= self.config.max_ops
        if not due and self.config.max_time_ms is not None and self.clock:
            due = (self.clock() - self.last_summary_time
                   ) * 1000.0 >= self.config.max_time_ms
        if due:
            self.summarize_now(reason="heuristics")

    def _on_ack(self, message: SequencedDocumentMessage) -> None:
        self.ops_since_ack = 0
        self.last_ack_seq = message.contents["summary_proposal"][
            "summary_sequence_number"]
        if self.clock:
            self.last_summary_time = self.clock()
        handle = message.contents.get("handle")
        if self.pending_handle is not None and handle == self.pending_handle:
            self.pending_handle = None
            self.last_acked_gen_seq = self.pending_gen_seq
        else:
            # A peer's summary: we can't know which seq it captured, so
            # the next summary we generate is full.
            self.last_acked_gen_seq = None
        self.last_acked_handle = handle
        self.events.append(SummarizerEvent(
            "acked", message.sequence_number, handle=handle))

    def _on_nack(self, message: SequencedDocumentMessage) -> None:
        # Clear in-flight only when the rejection is for OUR offer — a
        # peer's bogus offer being nacked must not cancel ours.
        handle = (message.contents or {}).get("handle")
        if self.pending_handle is not None and handle == self.pending_handle:
            self.pending_handle = None
        self.events.append(SummarizerEvent(
            "nacked", message.sequence_number, handle=handle,
            reason=(message.contents or {}).get("message")))

    def _on_transport_nack(self, nack) -> None:
        # The sequencer itself can reject the SUMMARIZE op (drain mode,
        # refSeq below MSN after a gap): that arrives as a transport NACK,
        # never as a sequenced SUMMARY_NACK — clear in-flight so summaries
        # don't stall forever.
        operation = getattr(nack, "operation", None)
        if operation is None or operation.type != MessageType.SUMMARIZE:
            return
        if (operation.contents or {}).get("handle") == self.pending_handle:
            self.pending_handle = None

    # -- generation ------------------------------------------------------------

    def summarize_now(self, reason: str = "manual") -> str | None:
        """Generate + upload + offer a summary. Returns the handle, or None
        when not connected/attached."""
        container = self.container
        if not container.connected or not container.attached:
            return None
        if container.runtime.pending.has_pending:
            return None  # unacked optimistic state; see _on_op
        incremental = (self.last_acked_handle is not None
                       and self.last_acked_gen_seq is not None)
        summary = container.summarize(
            unchanged_before=self.last_acked_gen_seq if incremental
            else None)
        try:
            handle = container._service.storage.upload_snapshot(
                summary,
                parent=self.last_acked_handle if incremental else None)
        except Exception as err:
            # Upload/resolution failure (e.g. the parent summary was
            # pruned): record it, fall back to a FULL summary next time,
            # and never let the error escape into op processing.
            self.last_acked_gen_seq = None
            self.events.append(SummarizerEvent(
                "nacked", container.last_processed_seq,
                reason=f"upload failed: {err!r}"))
            return None
        self.pending_handle = handle
        self.pending_gen_seq = summary["sequence_number"]
        self.pending_since_seq = container.last_processed_seq
        # Record BEFORE submitting: the in-proc server delivers the ack
        # re-entrantly inside the submit call.
        self.events.append(SummarizerEvent(
            "generated", summary["sequence_number"], handle=handle,
            reason=reason))
        container.submit_message(MessageType.SUMMARIZE, {
            "handle": handle,
            "head": self.last_ack_seq,
            "message": reason,
        })
        return handle
