"""DataStoreRuntime — hosts named channels (DDS instances).

Reference parity: packages/runtime/datastore/src/dataStoreRuntime.ts:98
(``FluidDataStoreRuntime``: createChannel:370, process:499 routing the
envelope {address: channelId, contents} to the channel, channel summaries)
and channelDeltaConnection.ts:39.
"""

from __future__ import annotations

from dataclasses import replace
from typing import TYPE_CHECKING, Any

from ..dds.shared_object import ChannelRegistry, SharedObject
from ..protocol.messages import SequencedDocumentMessage

if TYPE_CHECKING:  # pragma: no cover
    from .container_runtime import ContainerRuntime


class ChannelDeltaConnection:
    """The submit/process pipe between one channel and its data store."""

    def __init__(self, datastore: "DataStoreRuntime", channel_id: str) -> None:
        self._datastore = datastore
        self._channel_id = channel_id

    def submit(self, contents: Any, local_op_metadata: Any) -> None:
        self._datastore.submit_channel_op(
            self._channel_id, contents, local_op_metadata)


class DataStoreRuntime:
    def __init__(self, datastore_id: str, parent: "ContainerRuntime",
                 registry: ChannelRegistry,
                 attributes: dict | None = None) -> None:
        self.id = datastore_id
        self.parent = parent
        self.registry = registry
        self.channels: dict[str, SharedObject] = {}
        # Snapshot-loaded channels realize LAZILY on first access
        # (remoteChannelContext.ts:203's lazy realization): until then the
        # stored snapshot (possibly a virtualized blob stub —
        # drivers/virtualized_driver.py) IS the channel's state. Keyed by
        # channel id; values are channel snapshots or virtual stubs.
        self._unrealized: dict[str, dict] = {}

        # Persisted metadata, e.g. {"type": <data-object type>} — what the
        # reference stores as the data store's package path so the right
        # DataObject class re-instantiates on load (dataStoreContext.ts).
        self.attributes: dict = attributes or {}
        # Channel ids created by ops voided in a lost concurrent-create
        # race: the first-sequenced attach_channel to arrive (the winner's,
        # or our own voided echo) reloads its snapshot into the existing
        # object in place. See adopt()/process().
        self._adoption_pending: set[str] = set()

    @property
    def handle(self):
        """A serializable FluidHandle to this data store."""
        from .handles import FluidHandle
        return FluidHandle(f"/{self.id}", self.resolve_path)

    def resolve_path(self, absolute_path: str):
        """Resolve ``/ds`` or ``/ds/channel`` via the container runtime."""
        return self.parent.resolve_path(absolute_path)

    def get_gc_data(self, summary: dict | None = None) -> dict[str, list[str]]:
        """GC graph fragment: this store's node (implicit edges to its
        channels) + each channel's node (its stored handle routes). Pass an
        already-built ``summarize()`` result to scan it instead of
        re-serializing channel state."""
        from .handles import collect_handle_routes
        from ..protocol.summary import is_handle
        live = [cid for cid in (set(self.channels) | set(self._unrealized))
                if cid not in self._adoption_pending]
        graph = {f"/{self.id}": [f"/{self.id}/{cid}"
                                 for cid in sorted(live)]}
        for channel_id in sorted(live):
            if channel_id in self._unrealized:
                # Routes come from the stored snapshot content — no
                # realization needed (a virtual stub resolves once, then
                # memoizes; GC runs on the summarizer, where the first
                # fetch is warranted).
                snap = self._stored_snapshot(channel_id)
                graph[f"/{self.id}/{channel_id}"] = collect_handle_routes(
                    snap["content"])
                continue
            channel = self.channels[channel_id]
            node = None if summary is None else \
                summary["channels"][channel_id]
            from ..drivers.virtualized_driver import is_virtual_stub
            if node is not None and not is_handle(node) \
                    and not is_virtual_stub(node):
                routes = collect_handle_routes(node["content"])
                # Seed the dirty-bit cache from the inline content so the
                # NEXT (incremental) summary's GC pass costs nothing for
                # this channel if it stays unchanged.
                channel._gc_cache = (channel.last_changed_seq, routes)
            else:
                # Handle stub (unchanged channel): routes come from the
                # channel's dirty-bit cache, not a re-serialization.
                routes = channel.gc_routes()
            graph[f"/{self.id}/{channel_id}"] = routes
        return graph

    # -- channel lifecycle ----------------------------------------------------

    def create_channel(self, channel_id: str, channel_type: str) -> SharedObject:
        if channel_id in self.channels or channel_id in self._unrealized:
            raise ValueError(f"channel {channel_id!r} already exists")
        channel = self.registry.get(channel_type).create(self, channel_id)
        self._bind(channel)
        if self.parent.container.attached:
            # Announce the new channel to peers (dataStoreRuntime.ts:405
            # bindChannel → attach op carrying the channel snapshot).
            self.parent.submit_datastore_op(
                self.id,
                {"type": "attach_channel", "address": channel_id,
                 "snapshot": channel.summarize()},
                None,
            )
        return channel

    def get_channel(self, channel_id: str) -> SharedObject:
        if channel_id in self._unrealized:
            self._realize(channel_id)
        return self.channels[channel_id]

    def channel_ids(self) -> list[str]:
        """Every channel id, realized or lazy (access via get_channel)."""
        return sorted(set(self.channels) | set(self._unrealized))

    def _unrealized_type(self, channel_id: str) -> str:
        """A lazy channel's DDS type WITHOUT realizing (stubs carry it)."""
        from ..drivers.virtualized_driver import VIRTUAL_KEY, is_virtual_stub
        snap = self._unrealized[channel_id]
        if is_virtual_stub(snap):
            return snap[VIRTUAL_KEY].get("type", "")
        return snap["attributes"]["type"]

    def channel_type(self, channel_id: str) -> str:
        """A channel's DDS type string without forcing realization — the
        public filter surface for agents/tools walking documents."""
        if channel_id in self._unrealized:
            return self._unrealized_type(channel_id)
        return self.channels[channel_id].attributes.get("type", "")

    def realize_membership_sensitive(self) -> None:
        """Realize lazy channels whose type reacts to quorum membership
        (e.g. consensus collections releasing a departed client's leases)
        — they must observe client-leave events even if the app never
        touched them."""
        for channel_id in list(self._unrealized):
            try:
                cls = self.registry.get(
                    self._unrealized_type(channel_id)).shared_object_cls
            except KeyError:
                continue
            if hasattr(cls, "on_client_leave"):
                self._realize(channel_id)

    def _stored_snapshot(self, channel_id: str) -> dict:
        """A lazy channel's full snapshot; a virtualized stub resolves
        ONCE and the resolution is memoized back into the store (the
        content cannot change while unrealized), so repeated GC/summary
        passes cost no further blob fetches."""
        from ..drivers.virtualized_driver import is_virtual_stub
        snapshot = self._unrealized[channel_id]
        if is_virtual_stub(snapshot):
            resolver = getattr(self.parent.container, "snapshot_resolver",
                               None)
            if resolver is None:
                raise KeyError(
                    "virtualized channel snapshot with no blob resolver")
            snapshot = resolver(snapshot)
            self._unrealized[channel_id] = snapshot
        return snapshot

    def _realize(self, channel_id: str) -> None:
        """First access to a snapshot-loaded channel: resolve its (maybe
        virtualized) snapshot and construct the live object. The lazy
        entry is removed only after construction SUCCEEDS — a failed
        load (unknown type, bad snapshot) must keep the channel visible
        to channel_ids()/summarize(), exactly as the eager path failed
        loudly without losing data."""
        snapshot = self._stored_snapshot(channel_id)
        channel_type = snapshot["attributes"]["type"]
        channel = self.registry.get(channel_type).load(
            self, channel_id, snapshot)
        self._bind(channel)
        self._unrealized.pop(channel_id)
        # last_changed_seq stays at the construction default, exactly as
        # the eager load path leaves it — summaries must not depend on
        # WHEN a replica realized a channel.

    def _bind(self, channel: SharedObject) -> None:
        self.channels[channel.id] = channel
        channel.bind_connection(ChannelDeltaConnection(self, channel.id))

    # -- op plumbing ---------------------------------------------------------

    def submit_channel_op(self, channel_id: str, contents: Any,
                          local_op_metadata: Any) -> None:
        self.parent.submit_datastore_op(
            self.id,
            {"address": channel_id, "contents": contents},
            local_op_metadata,
        )

    def process(self, message: SequencedDocumentMessage, local: bool,
                local_op_metadata: Any) -> None:
        envelope = message.contents
        if envelope.get("type") == "attach_channel":
            changed = self._process_attach(envelope, local)
            # Stamp the dirty bit ONLY when the attach changed channel
            # state (creation/adoption — such a channel must summarize
            # inline; a handle stub would dangle, protocol/summary.py).
            # An IGNORED attach (the existing channel won the race) must
            # not stamp: whether the loser arrived is unrelated to the
            # channel's content, and stamping here would make summaries
            # depend on whether a replica had realized a lazy channel.
            if changed or local:
                created = self.channels.get(envelope["address"])
                if created is not None:
                    created.last_changed_seq = message.sequence_number
            return
        channel = self.get_channel(envelope["address"])
        channel.process(
            replace(message, contents=envelope["contents"]),
            local,
            local_op_metadata,
        )

    def _process_attach(self, envelope: dict, local: bool) -> bool:
        """Returns True when the attach CHANGED state (created/adopted a
        channel) — the caller stamps the dirty bit only then, so the
        outcome is identical on every replica regardless of lazy
        realization."""
        if local:
            return False
        address = envelope["address"]
        if address in self._unrealized:
            # A lazy snapshot-loaded channel was never locally pending,
            # so the remote attach can only lose to it (our channel
            # already exists on every replica's snapshot) — drop the
            # stale attach WITHOUT realizing (no blob fetch on the
            # op-processing path).
            return False
        if address not in self.channels:
            self._adopt_channel(address, envelope["snapshot"])
            return True
        if address in self._adoption_pending:
            # Datastore-race leftover: the FIRST sequenced
            # attach_channel for this id (winner's, or our own voided
            # echo) defines its state on every replica.
            self._adopt_channel(address, envelope["snapshot"])
            return True
        # Same-id channel create race on a shared datastore: if OUR
        # create of this channel is still pending, the remote
        # attach_channel sequenced first — adopt its snapshot and void
        # our pending create + ops (their echoes re-apply as remote
        # ops, like every replica). Otherwise our create already won:
        # ignore the later one (all replicas do).
        if self.parent.void_channel(self.id, address):
            self._adopt_channel(address, envelope["snapshot"])
            return True
        return False

    def resubmit(self, envelope: dict, local_op_metadata: Any) -> None:
        if envelope.get("type") == "attach_channel":
            # Re-announce with the ORIGINAL create-time snapshot — edits made
            # since are their own pending ops and replay right after this
            # (re-snapshotting here would double-apply them on remotes).
            self.parent.submit_datastore_op(self.id, envelope, None)
            return
        channel = self.get_channel(envelope["address"])
        channel.resubmit(envelope["contents"], local_op_metadata)

    def adopt(self, snapshot: dict) -> None:
        """Replace this store's state with a concurrent-create winner's
        snapshot IN PLACE: channels sharing id+type reload their state into
        the existing objects, so references held by app code stay live (and
        keep submitting/processing against the adopted state). Channels
        absent from the winner's snapshot were announced by our now-voided
        attach_channel ops — they stay, marked adoption-pending, and the
        first-sequenced attach_channel to arrive for that id (the winner's
        or our own voided echo) reloads its snapshot into them, which is
        exactly the state every remote replica builds."""
        self.attributes = snapshot.get("attributes", {})
        winner_channels = snapshot["channels"]
        for channel_id in list(self._unrealized):
            # Lazy channels participate in adoption like realized ones.
            self._realize(channel_id)
        for channel_id in self.channels:
            if channel_id not in winner_channels:
                self._adoption_pending.add(channel_id)
        for channel_id, channel_snapshot in winner_channels.items():
            self._adopt_channel(channel_id, channel_snapshot)

    def _adopt_channel(self, channel_id: str, snapshot: dict) -> None:
        """Reload a channel snapshot into the existing object (keeping its
        identity) when the types agree, else rebind a fresh instance. Any
        local ops still pending against the pre-adopt state are voided —
        their echoes apply as remote ops, exactly as every replica applies
        them to the adopted state."""
        self._adoption_pending.discard(channel_id)
        self._unrealized.pop(channel_id, None)  # superseded before access
        self.parent.void_channel_ops(self.id, channel_id)
        channel_type = snapshot["attributes"]["type"]
        existing = self.channels.get(channel_id)
        if (existing is not None
                and existing.attributes.get("type") == channel_type):
            existing.load(snapshot)
        else:
            self._bind(self.registry.get(channel_type).load(
                self, channel_id, snapshot))

    def void_adoption_pending_ops(self) -> None:
        """Reconnect while channel adoptions are still unresolved: pending
        ops against those channels must not replay (the state they were
        recorded against is provisional; if the adopting attach_channel was
        sequenced it arrives in catch-up and its ops with it). The channels
        themselves stay, still marked — catch-up may yet adopt them, and
        until then summarize()/GC exclude them."""
        for channel_id in self._adoption_pending:
            self.parent.void_channel_ops(self.id, channel_id)

    # -- summary --------------------------------------------------------------

    def summarize(self, unchanged_before: int | None = None) -> dict:
        # Adoption-pending channels are provisional local state: on every
        # other replica they either do not exist yet or will be defined by
        # the first-sequenced attach_channel — excluding them keeps
        # summaries byte-identical across replicas during the race window.
        #
        # Incremental mode (summary.ts:53 handle reuse): channels whose
        # last change is at or below ``unchanged_before`` (the last ACKED
        # summary's seq) serialize as handle stubs into that summary
        # instead of full content — O(changed) summaries.
        from ..protocol.summary import make_handle

        channels: dict[str, dict] = {}
        ids = sorted(set(self.channels) | set(self._unrealized))
        for channel_id in ids:
            if channel_id in self._adoption_pending:
                continue
            if channel_id in self._unrealized:
                # Never accessed since load: unchanged by definition. In
                # incremental mode it stubs like any unchanged channel;
                # a full summary re-inlines the (resolved) snapshot.
                if unchanged_before is not None:
                    channels[channel_id] = make_handle(
                        f"runtime/datastores/{self.id}/channels/"
                        f"{channel_id}")
                else:
                    channels[channel_id] = self._stored_snapshot(
                        channel_id)
                continue
            channel = self.channels[channel_id]
            if (unchanged_before is not None
                    and channel.last_changed_seq <= unchanged_before):
                channels[channel_id] = make_handle(
                    f"runtime/datastores/{self.id}/channels/{channel_id}")
            else:
                channels[channel_id] = channel.summarize()
        return {
            "attributes": dict(sorted(self.attributes.items())),
            "channels": channels,
        }

    def load(self, snapshot: dict) -> None:
        """Defer channel construction: the stored snapshots realize on
        first access (lazy realization, remoteChannelContext.ts:203) —
        with a virtualizing driver a stubbed channel's content is not
        even FETCHED until then."""
        self.attributes = snapshot.get("attributes", {})
        self._unrealized.update(snapshot["channels"])
