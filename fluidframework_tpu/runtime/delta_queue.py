"""DeltaQueue — pausable single-consumer FIFO.

Reference parity: packages/loader/container-loader/src/deltaQueue.ts:10.
Pausing is the test-orchestration primitive the reference uses for
deterministic op interleaving (test-utils OpProcessingController).
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Generic, TypeVar

T = TypeVar("T")


class DeltaQueue(Generic[T]):
    def __init__(self, handler: Callable[[T], None]) -> None:
        self._handler = handler
        self._queue: deque[T] = deque()
        self._pause_count = 1  # starts paused; resume() when connected
        self._processing = False

    def __len__(self) -> int:
        return len(self._queue)

    @property
    def paused(self) -> bool:
        return self._pause_count > 0

    def push(self, item: T) -> None:
        self._queue.append(item)
        self._drain()

    def pause(self) -> None:
        self._pause_count += 1

    def resume(self) -> None:
        assert self._pause_count > 0, "resume without matching pause"
        self._pause_count -= 1
        self._drain()

    def clear(self) -> None:
        """Drop all queued items (outbound teardown on disconnect: pending
        ops resubmit with fresh clientSeqNumbers, never the stale batches)."""
        self._queue.clear()

    def process_one(self) -> bool:
        """Process a single item regardless of pause state (test stepping)."""
        if not self._queue:
            return False
        self._handler(self._queue.popleft())
        return True

    def _drain(self) -> None:
        if self._processing:
            return
        self._processing = True
        try:
            while self._queue and not self.paused:
                self._handler(self._queue.popleft())
        finally:
            self._processing = False
