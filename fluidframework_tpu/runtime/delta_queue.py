"""DeltaQueue — pausable single-consumer FIFO.

Reference parity: packages/loader/container-loader/src/deltaQueue.ts:10.
Pausing is the test-orchestration primitive the reference uses for
deterministic op interleaving (test-utils OpProcessingController).
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Generic, TypeVar

T = TypeVar("T")


class DeltaQueue(Generic[T]):
    def __init__(self, handler: Callable[[T], None],
                 scheduler: "DeltaScheduler | None" = None) -> None:
        self._handler = handler
        self._queue: deque[T] = deque()
        self._pause_count = 1  # starts paused; resume() when connected
        self._processing = False
        self.scheduler = scheduler

    def __len__(self) -> int:
        return len(self._queue)

    @property
    def paused(self) -> bool:
        return self._pause_count > 0

    def push(self, item: T) -> None:
        self._queue.append(item)
        self._drain()

    def pause(self) -> None:
        self._pause_count += 1

    def resume(self) -> None:
        assert self._pause_count > 0, "resume without matching pause"
        self._pause_count -= 1
        self._drain()

    def clear(self) -> None:
        """Drop all queued items (outbound teardown on disconnect: pending
        ops resubmit with fresh clientSeqNumbers, never the stale batches)."""
        self._queue.clear()

    def process_one(self) -> bool:
        """Process a single item regardless of pause state (test stepping)."""
        if not self._queue:
            return False
        self._handler(self._queue.popleft())
        return True

    def _drain(self) -> None:
        if self._processing:
            return
        self._processing = True
        try:
            if self.scheduler is not None:
                self.scheduler.on_drain_start(len(self._queue))
            processed = 0
            while self._queue and not self.paused:
                self._handler(self._queue.popleft())
                processed += 1
                if self.scheduler is not None:
                    self.scheduler.on_processed(processed, len(self._queue))
        finally:
            self._processing = False


class DeltaScheduler:
    """Inbound catch-up yielding (container-runtime deltaScheduler.ts:25).

    The reference interrupts a long synchronous inbound drain so the JS
    thread can paint. The Python analog: after each ``batch_size`` ops in
    one drain, the registered ``on_yield`` callbacks run (host event
    pumps, progress UI, watchdog kicks) before processing continues."""

    DEFAULT_BATCH = 64

    def __init__(self, batch_size: int = DEFAULT_BATCH) -> None:
        self.batch_size = batch_size
        self.on_yield: list[Callable[[int, int], None]] = []
        self.catch_up_drains = 0  # drains that started with a deep queue

    def on_drain_start(self, queued: int) -> None:
        if queued > self.batch_size:
            self.catch_up_drains += 1

    def on_processed(self, processed: int, remaining: int) -> None:
        if processed % self.batch_size == 0 and remaining:
            for cb in self.on_yield:
                cb(processed, remaining)
