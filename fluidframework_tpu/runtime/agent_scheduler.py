"""AgentScheduler — distributed task leasing + leader election.

Reference parity: packages/runtime/agent-scheduler/src/scheduler.ts — tasks
are claimed by writing the claimant's clientId into a
ConsensusRegisterCollection register (linearizable at sequencing, so the
first sequenced claim wins); when the claimant leaves the quorum, interested
clients volunteer again. Leader election = picking the well-known "leader"
task (scheduler.ts leadership helper).
"""

from __future__ import annotations

from typing import Callable

from ..dds.register_collection import ConsensusRegisterCollection
from .container import Container

UNCLAIMED = None
LEADER_TASK = "leader"


class AgentScheduler:
    DATASTORE_ID = "_agent_scheduler"
    CHANNEL_ID = "tasks"

    def __init__(self, container: Container,
                 channel: ConsensusRegisterCollection) -> None:
        self.container = container
        self._tasks = channel
        # task id → callback to run when (re)claimed by this client.
        self._interested: dict[str, Callable[[], None] | None] = {}
        self._held: set[str] = set()
        self._in_flight: set[str] = set()  # volunteer writes not yet decided
        self._tasks.on_op.append(lambda _msg, _local: self._evaluate())
        container.protocol.quorum.on_remove_member.append(
            self._on_member_removed)
        # A pick() made while disconnected volunteers on (re)connect.
        container.on_connected.append(lambda _cid: self._evaluate())

    # -- wiring ---------------------------------------------------------------

    @classmethod
    def get(cls, container: Container) -> "AgentScheduler":
        """Create-or-open the scheduler's hidden data store (the reference
        mounts it at the well-known "_scheduler" route). Idempotent: one
        scheduler instance per container (cached), or double-subscribed
        hooks would disagree about held tasks."""
        existing = getattr(container, "_agent_scheduler", None)
        if existing is not None:
            return existing
        try:
            datastore = container.runtime.get_datastore(cls.DATASTORE_ID)
        except KeyError:
            datastore = container.runtime.create_datastore(cls.DATASTORE_ID)
            datastore.create_channel(
                cls.CHANNEL_ID, ConsensusRegisterCollection.channel_type)
        scheduler = cls(container, datastore.get_channel(cls.CHANNEL_ID))
        container._agent_scheduler = scheduler
        return scheduler

    # -- task API (scheduler.ts pick/release/pickedTasks) ---------------------

    def pick(self, task_id: str,
             callback: Callable[[], None] | None = None) -> None:
        """Register interest: claim the task if unclaimed, and re-volunteer
        whenever the current claimant leaves."""
        self._interested[task_id] = callback
        if self.claimant(task_id) is UNCLAIMED:
            self._volunteer(task_id)
        else:
            self._evaluate()

    def release(self, task_id: str) -> None:
        """Give the task up (only valid while holding it)."""
        assert task_id in self._held, f"not holding {task_id!r}"
        self._interested.pop(task_id, None)
        self._held.discard(task_id)
        self._tasks.write(task_id, UNCLAIMED)

    def claimant(self, task_id: str) -> str | None:
        """Current valid holder: the consensus register value (atomic read =
        first sequenced claim), but only while that client is a quorum
        member — a claim stamped with a departed/stale id (e.g. a volunteer
        write replayed across a reconnect under the old identity) is void,
        exactly as the reference validates picks against the quorum
        (scheduler.ts pickCore)."""
        raw = self._tasks.read(task_id, ConsensusRegisterCollection.ATOMIC)
        if raw is UNCLAIMED:
            return UNCLAIMED
        if raw not in self.container.protocol.quorum.get_members():
            return UNCLAIMED
        return raw

    def picked_tasks(self) -> list[str]:
        return sorted(self._held)

    # -- leadership ------------------------------------------------------------

    def volunteer_for_leadership(
            self, on_elected: Callable[[], None] | None = None) -> None:
        self.pick(LEADER_TASK, on_elected)

    @property
    def leader(self) -> str | None:
        return self.claimant(LEADER_TASK)

    @property
    def is_leader(self) -> bool:
        client_id = self.container.client_id
        return client_id is not None and self.leader == client_id

    # -- claim machinery -------------------------------------------------------

    def _volunteer(self, task_id: str) -> None:
        if self.container.client_id is None or task_id in self._in_flight:
            return
        self._in_flight.add(task_id)
        self._tasks.write(task_id, self.container.client_id)

    def _evaluate(self) -> None:
        """After any sequenced write: fire callbacks for newly-won tasks and
        re-volunteer for interested tasks that became unclaimed (voluntary
        release by the previous holder)."""
        # Snapshot: a callback may pick() more tasks mid-iteration.
        client_id = self.container.client_id
        for task_id, callback in list(self._interested.items()):
            claimant = self.claimant(task_id)
            if claimant is not UNCLAIMED:
                self._in_flight.discard(task_id)  # the race was decided
            held = client_id is not None and claimant == client_id
            if held and task_id not in self._held:
                self._held.add(task_id)
                if callback is not None:
                    callback()
            elif not held:
                self._held.discard(task_id)
                if claimant is UNCLAIMED:
                    self._volunteer(task_id)

    def _on_member_removed(self, _client_id: str) -> None:
        # The quorum has already dropped the member by the time this callback
        # fires, so claimant() for any task they held now reads UNCLAIMED —
        # re-run the claim loop, which re-volunteers for every interested
        # unclaimed task (scheduler.ts pick-on-leave).
        self._evaluate()
