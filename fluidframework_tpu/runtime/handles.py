"""Fluid handles — serializable references between stored objects.

Reference parity: packages/loader/core-interfaces (IFluidHandle),
packages/dds/shared-object-base/src/handle.ts (``SharedObjectHandle``) and
runtime-utils handle encoding: a handle is a JSON-encodable pointer
``{"type": "__fluid_handle__", "url": "/datastoreId[/channelId]"}`` that a
DDS can store as a value. Handles are what the reference-graph GC walks
(packages/runtime/garbage-collector): every stored handle is an outbound
GC route from the DDS that stores it to the routed node.
"""

from __future__ import annotations

from typing import Any, Callable

HANDLE_MARKER = "__fluid_handle__"


class FluidHandle:
    """A reference to a data store (``/ds``) or channel (``/ds/channel``)."""

    def __init__(self, absolute_path: str,
                 resolver: "Callable[[str], Any] | None" = None) -> None:
        assert absolute_path.startswith("/"), absolute_path
        self.absolute_path = absolute_path
        self._resolver = resolver

    def get(self) -> Any:
        """Resolve to the live DataStoreRuntime / SharedObject."""
        if self._resolver is None:
            raise RuntimeError(
                f"handle {self.absolute_path!r} is not bound to a runtime")
        return self._resolver(self.absolute_path)

    def __eq__(self, other: object) -> bool:
        return (isinstance(other, FluidHandle)
                and other.absolute_path == self.absolute_path)

    def __hash__(self) -> int:
        return hash(self.absolute_path)

    def __repr__(self) -> str:  # pragma: no cover
        return f"FluidHandle({self.absolute_path!r})"


def encode_value(value: Any) -> Any:
    """Deep-encode: FluidHandle → wire marker dict (handle.ts toJSON)."""
    if isinstance(value, FluidHandle):
        return {"type": HANDLE_MARKER, "url": value.absolute_path}
    if isinstance(value, dict):
        return {k: encode_value(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [encode_value(v) for v in value]
    return value


def decode_value(value: Any, resolver: Callable[[str], Any] | None) -> Any:
    """Deep-decode: wire marker dict → FluidHandle bound to ``resolver``.

    Handle-free values are returned as-is (no copy) so reads keep
    reference semantics and O(1) cost for the common case.
    """
    if not _has_marker(value):
        return value
    if is_handle_marker(value):
        return FluidHandle(value["url"], resolver)
    if isinstance(value, dict):
        return {k: decode_value(v, resolver) for k, v in value.items()}
    if isinstance(value, list):
        return [decode_value(v, resolver) for v in value]
    return value


def _has_marker(value: Any) -> bool:
    if is_handle_marker(value) or isinstance(value, FluidHandle):
        return True
    if isinstance(value, dict):
        return any(_has_marker(v) for v in value.values())
    if isinstance(value, (list, tuple)):
        return any(_has_marker(v) for v in value)
    return False


def is_handle_marker(value: Any) -> bool:
    return (isinstance(value, dict) and value.get("type") == HANDLE_MARKER
            and isinstance(value.get("url"), str))


def collect_handle_routes(value: Any) -> list[str]:
    """All handle routes stored anywhere inside ``value`` (GC outbound
    edges; runtime-utils' equivalent scans serialized summary content)."""
    routes: list[str] = []
    _collect(value, routes)
    return routes


def _collect(value: Any, out: list[str]) -> None:
    if is_handle_marker(value):
        out.append(value["url"])
        return
    if isinstance(value, FluidHandle):
        out.append(value.absolute_path)
        return
    if isinstance(value, dict):
        for v in value.values():
            _collect(v, out)
    elif isinstance(value, (list, tuple)):
        for v in value:
            _collect(v, out)
