"""Loader + code loader — quorum-"code"-driven runtime instantiation.

Reference parity: packages/loader/container-loader/src/loader.ts:103
(``Loader.resolve``: URL → driver → Container) and the code-loading
boundary the reference machine-enforces: the loader knows NOTHING about
app code; the quorum's committed ``"code"`` value names the runtime
factory, fetched through an ``ICodeLoader``
(container.ts:1700-1835, web-code-loader/src/webLoader.ts). Here the
"app code" a factory supplies is the channel registry (which DDS types
exist) plus any bootstrap — the IRuntimeFactory.instantiateRuntime
surface collapsed to :meth:`RuntimeFactory.instantiate`.

Create flow: ``create_detached`` seeds the committed ``code`` value into
the detached quorum (shipped via the attach snapshot) so every later
``resolve`` can pick the right factory before any channel instantiates.
"""

from __future__ import annotations

from typing import Callable, Protocol
from urllib.parse import urlparse

from ..dds.shared_object import ChannelRegistry
from ..drivers.base import DocumentService
from .container import Container

CODE_KEY = "code"


class RuntimeFactory(Protocol):
    """instantiateRuntime seam (container-definitions IRuntimeFactory)."""

    def instantiate(self, container: Container) -> None: ...


class StaticRuntimeFactory:
    """A runtime factory that is just a channel registry (the minimum
    viable 'app code')."""

    def __init__(self, registry: ChannelRegistry) -> None:
        self.registry = registry

    def instantiate(self, container: Container) -> None:
        container.runtime.registry = self.registry


class CodeLoader:
    """web-code-loader analog: resolves code details → runtime factory.

    The reference fetches a UMD bundle named by
    ``{package, version}``; here packages register in-process."""

    def __init__(self) -> None:
        self._packages: dict[tuple[str, str], RuntimeFactory] = {}

    def register(self, package: str, factory: RuntimeFactory,
                 version: str = "1.0.0") -> None:
        self._packages[(package, version)] = factory

    def load(self, code_details: dict | None) -> RuntimeFactory:
        if not isinstance(code_details, dict) or "package" not in code_details:
            raise ValueError(f"malformed code details: {code_details!r}")
        key = (code_details["package"], code_details.get("version", "1.0.0"))
        if key not in self._packages:
            raise KeyError(f"no code registered for {key}")
        return self._packages[key]


class Loader:
    """Resolve document URLs to running containers (loader.ts:307).

    URLs look like ``fluid://<host>/<doc_id>``; the service factory maps a
    doc id to a DocumentService (the driver seam), mirroring the
    reference's UrlResolver + IDocumentServiceFactory pair."""

    def __init__(self, service_factory: Callable[[str], DocumentService],
                 code_loader: CodeLoader) -> None:
        self._service_factory = service_factory
        self.code_loader = code_loader

    @staticmethod
    def _doc_id(url: str) -> str:
        if "://" not in url:
            return url
        parsed = urlparse(url)
        doc_id = parsed.path.lstrip("/")
        if not doc_id:
            raise ValueError(f"no document id in {url!r}")
        return doc_id

    def resolve(self, url: str, mode: str = "write",
                pending_state: dict | None = None) -> Container:
        """Open an existing document; the quorum's committed ``code``
        value picks the runtime factory before any channel loads."""
        service = self._service_factory(self._doc_id(url))
        return Container.load(service, mode=mode,
                              pending_state=pending_state,
                              code_loader=self.code_loader)

    def create_detached(self, code_details: dict,
                        url: str) -> Container:
        """New detached document running the given code; the committed
        code value ships in the attach snapshot."""
        factory = self.code_loader.load(code_details)
        service = self._service_factory(self._doc_id(url))
        container = Container.create_detached(service)
        container.protocol.quorum.set_local_value(CODE_KEY, code_details)
        factory.instantiate(container)
        return container
