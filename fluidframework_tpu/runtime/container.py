"""Container — a client's connection to one collaborative document.

Reference parity: packages/loader/container-loader/src/container.ts
(``Container``: load:277/1115, processRemoteMessage:1700, connection state)
with the DeltaManager inbound/outbound queues (deltaManager.ts:147,197-199)
collapsed into one class — transport is a driver-provided delta connection;
storage is a driver-provided snapshot/delta reader.

The container owns the protocol handler (quorum) and the ContainerRuntime;
protocol messages route to the former, OPERATION envelopes to the latter.
"""

from __future__ import annotations

from typing import Any, Callable

from ..drivers.base import DocumentService
from ..protocol.handler import ProtocolOpHandler
from ..protocol.messages import (
    DocumentMessage,
    MessageType,
    SequencedDocumentMessage,
)
from .container_runtime import ContainerRuntime
from .delta_queue import DeltaQueue


class Container:
    def __init__(self, document_service: DocumentService,
                 registry=None) -> None:
        self._service = document_service
        self.protocol = ProtocolOpHandler()
        self.runtime = ContainerRuntime(self, registry)
        self._wire_quorum()
        self.client_id: str | None = None
        self.attached = False
        self._connection: Any = None
        self.client_seq = 0
        self.last_processed_seq = 0
        self.inbound: DeltaQueue[SequencedDocumentMessage] = DeltaQueue(
            self._process_remote_message)
        self.on_connected: list[Callable[[str], None]] = []
        self.on_disconnected: list[Callable[[], None]] = []
        # Service rejections of our ops (never silent — tests assert empty).
        self.nacks: list[Any] = []
        self.on_nack: list[Callable[[Any], None]] = []

    # -- load -----------------------------------------------------------------

    @classmethod
    def load(cls, document_service: DocumentService, registry=None
             ) -> "Container":
        """Open an existing document: snapshot + trailing deltas + connect."""
        container = cls(document_service, registry)
        snapshot = document_service.storage.get_latest_snapshot()
        if snapshot is not None:
            container.protocol = ProtocolOpHandler.load(snapshot["protocol"])
            container._wire_quorum()
            container.runtime.load(snapshot["runtime"])
            container.last_processed_seq = snapshot["sequence_number"]
        container.attached = True
        container.connect()
        return container

    @classmethod
    def create_detached(cls, document_service: DocumentService, registry=None
                        ) -> "Container":
        """Create a new (empty) document; call attach() to go live. Edits made
        while detached apply locally and ship via the attach-time snapshot."""
        return cls(document_service, registry)

    def attach(self) -> None:
        """Publish the detached state as the document's base snapshot and go
        live (container.ts attach: detached → attached lifecycle)."""
        assert not self.attached, "already attached"
        self.runtime.on_attach()
        self._service.storage.upload_snapshot(self.summarize())
        self.attached = True
        self.connect()

    def _wire_quorum(self) -> None:
        """Membership events fan out to interested channels (e.g. consensus
        queues auto-release a departed client's leases)."""
        self.protocol.quorum.on_remove_member.append(self._on_member_removed)

    def _on_member_removed(self, client_id: str) -> None:
        for datastore in self.runtime.datastores.values():
            for channel in datastore.channels.values():
                on_leave = getattr(channel, "on_client_leave", None)
                if on_leave is not None:
                    on_leave(client_id)

    # -- connection state machine --------------------------------------------

    @property
    def connected(self) -> bool:
        return self._connection is not None

    def connect(self) -> None:
        assert self._connection is None, "already connected"
        # Catch up on deltas missed while away BEFORE the live stream starts;
        # both land in the paused inbound queue in seq order (the reference's
        # fetchMissingDeltas + early-op queueing, deltaManager.ts:1298-1360).
        for message in self._service.delta_storage.get_deltas(
                self.last_processed_seq):
            self.inbound.push(message)
        connection = self._service.connect(self._on_incoming,
                                           on_nack=self._on_nack)
        self._connection = connection
        self.client_id = connection.client_id
        self.client_seq = 0
        self.inbound.resume()
        for cb in self.on_connected:
            cb(connection.client_id)

    def disconnect(self) -> None:
        if self._connection is None:
            return
        self._connection.close()
        self._connection = None
        self.client_id = None
        self.inbound.pause()
        for cb in self.on_disconnected:
            cb()

    def reconnect(self) -> None:
        """Drop + re-establish the connection, replaying pending local ops
        (deltaManager.ts:566-692 + containerRuntime replayPendingStates)."""
        self.disconnect()
        self.connect()
        self.runtime.replay_pending()

    # -- outbound -------------------------------------------------------------

    def allocate_client_seq(self) -> int | None:
        """Claim the next clientSequenceNumber, or None when disconnected.
        Callers record pending state against it BEFORE send_message — the
        ack may arrive re-entrantly during the send (in-proc server)."""
        if self._connection is None:
            return None
        self.client_seq += 1
        return self.client_seq

    def send_message(self, mtype: MessageType, contents: Any,
                     client_seq: int) -> None:
        self._connection.submit([DocumentMessage(
            client_sequence_number=client_seq,
            reference_sequence_number=self.last_processed_seq,
            type=mtype,
            contents=contents,
        )])

    def submit_message(self, mtype: MessageType, contents: Any) -> int | None:
        """Stamp + send a message with no pending tracking (protocol msgs).
        Returns clientSequenceNumber, or None when not connected."""
        client_seq = self.allocate_client_seq()
        if client_seq is not None:
            self.send_message(mtype, contents, client_seq)
        return client_seq

    def propose(self, key: str, value: Any) -> None:
        self.submit_message(MessageType.PROPOSE, {"key": key, "value": value})

    # -- inbound --------------------------------------------------------------

    def _on_incoming(self, messages: list[SequencedDocumentMessage]) -> None:
        for message in messages:
            self.inbound.push(message)

    def _on_nack(self, nack: Any) -> None:
        self.nacks.append(nack)
        for cb in self.on_nack:
            cb(nack)

    def _process_remote_message(self, message: SequencedDocumentMessage) -> None:
        local = (
            self.client_id is not None and message.client_id == self.client_id
        )
        if message.sequence_number <= self.last_processed_seq:
            return  # duplicate during catch-up overlap
        assert message.sequence_number == self.last_processed_seq + 1, (
            f"sequence gap: got {message.sequence_number}, "
            f"expected {self.last_processed_seq + 1}"
        )
        self.last_processed_seq = message.sequence_number
        result = self.protocol.process_message(message, local)
        if message.type == MessageType.OPERATION:
            self.runtime.process(message, local)
        if result["immediate_noop"] and self.connected:
            # Expedite proposal commit (quorum.ts:326): a contentful noop revs
            # and carries our advanced refSeq to the sequencer.
            self.submit_message(MessageType.NOOP, "")

    # -- summary --------------------------------------------------------------

    def summarize(self) -> dict:
        """Full summary of protocol + runtime state at the current seq."""
        return {
            "sequence_number": self.last_processed_seq,
            "protocol": self.protocol.snapshot(),
            "runtime": self.runtime.summarize(),
        }

    def close(self) -> None:
        self.disconnect()
