"""Container — a client's connection to one collaborative document.

Reference parity: packages/loader/container-loader/src/container.ts
(``Container``: load:277/1115, processRemoteMessage:1700, connection state)
over a :class:`fluidframework_tpu.runtime.delta_manager.DeltaManager`
(deltaManager.ts:147 — inbound/outbound queues, gap fetch, readonly) —
transport is a driver-provided delta connection; storage is a
driver-provided snapshot/delta reader.

The container owns the protocol handler (quorum) and the ContainerRuntime;
protocol messages route to the former, OPERATION envelopes to the latter.
"""

from __future__ import annotations

from typing import Any, Callable

from ..drivers.base import DocumentService
from ..protocol.handler import ProtocolOpHandler
from ..protocol.messages import (
    MessageType,
    SequencedDocumentMessage,
)
from .container_runtime import ContainerRuntime
from .delta_manager import DeltaManager


class Audience:
    """Every connected client of the document — INCLUDING read-only
    connections, which never enter the quorum (container.ts:1700 region's
    audience wiring; the quorum tracks write clients only). Fed by
    service-emitted ``__audience__`` signals."""

    def __init__(self) -> None:
        self.members: dict[str, dict] = {}
        # Exact audience size from the service (interest-sampled presence:
        # a snapshot past the roster bound lists a member SAMPLE but
        # always carries the true total — server/audience.py).
        self.total = 0
        self.on_add_member: list[Callable[[str, dict], None]] = []
        self.on_remove_member: list[Callable[[str, dict], None]] = []

    def get_members(self) -> dict[str, dict]:
        return dict(self.members)

    def get_member(self, client_id: str) -> dict | None:
        return self.members.get(client_id)

    def _apply(self, payload: dict) -> None:
        event = payload.get("event")
        if event == "snapshot":
            self.members = {m["client_id"]: dict(m)
                            for m in payload.get("members", [])}
            self.total = payload.get("total", len(self.members))
        elif event == "join":
            member = dict(payload["member"])
            if member["client_id"] not in self.members:
                self.total += 1
            self.members[member["client_id"]] = member
            for cb in self.on_add_member:
                cb(member["client_id"], member)
        elif event == "leave":
            member = self.members.pop(payload.get("client_id"), None)
            self.total = max(0, self.total - 1)
            if member is not None:
                for cb in self.on_remove_member:
                    cb(payload["client_id"], member)
        elif event == "count":
            # Sampled-presence count update (server/audience.py past the
            # roster bound): the exact total, optionally naming a leaver
            # a peer's SAMPLE may still hold.
            self.total = payload.get("total", self.total)
            left = payload.get("left")
            if left is not None:
                member = self.members.pop(left, None)
                if member is not None:
                    for cb in self.on_remove_member:
                        cb(left, member)


class Container:
    def __init__(self, document_service: DocumentService,
                 registry=None) -> None:
        self._service = document_service
        self.protocol = ProtocolOpHandler()
        self.runtime = ContainerRuntime(self, registry)
        self._wire_quorum()
        self.attached = False
        self.delta_manager = DeltaManager(
            document_service,
            process_message=self._process_remote_message,
            process_signal=self._process_signal,
            on_nack=self._on_nack,
        )
        self._mode = "write"
        # Set by load() when the driver virtualizes channel snapshots.
        self.snapshot_resolver: Callable[[dict], dict] | None = None
        self.audience = Audience()
        self.on_connected: list[Callable[[str], None]] = []
        self.on_disconnected: list[Callable[[], None]] = []
        self.on_signal: list[Callable[[Any], None]] = []
        # Fired after every sequenced message is applied (summary manager,
        # telemetry, tests). Receives the SequencedDocumentMessage.
        self.on_op_processed: list[Callable[[SequencedDocumentMessage],
                                            None]] = []
        # Service rejections of our ops (never silent — tests assert empty).
        self.nacks: list[Any] = []
        self.on_nack: list[Callable[[Any], None]] = []
        # Offline resume: the previous session's client id while its
        # stashed ops may still arrive sequenced (cleared after catch-up).
        self._stashed_client_id: str | None = None
        # Transport-loss surfacing (ISSUE 5 satellite): drivers with an
        # event emitter (network driver "disconnect" on socket death)
        # degrade the container to disconnected/readonly instead of
        # leaving it hung on a dead socket.
        events = getattr(document_service, "events", None)
        if events is not None:
            events.on("disconnect", self._on_transport_lost)

    def _on_transport_lost(self) -> None:
        """The driver's transport died underneath us: drop the connection
        WITHOUT the disconnect RPC (no socket to carry it) and fire the
        disconnected callbacks. The container keeps serving local reads
        (readonly degradation); connect()/reconnect() — or an
        AutoReconnector — restores write mode."""
        if not self.connected:
            return
        self.delta_manager.handle_connection_lost()
        for cb in self.on_disconnected:
            cb()

    # -- load -----------------------------------------------------------------

    @classmethod
    def load(cls, document_service: DocumentService, registry=None,
             mode: str = "write", pending_state: dict | None = None,
             code_loader=None) -> "Container":
        """Open an existing document: snapshot + trailing deltas + connect.

        ``pending_state`` (from :meth:`close_and_get_pending_state`)
        resumes an offline session: stashed unacked ops re-apply locally
        via each channel's ``apply_stashed_op`` before catch-up; ops the
        old connection DID get sequenced ack against the stash during
        catch-up (matched by the stashed client id + clientSeq, the
        pendingStateManager.ts stashed-ops flow), and the remainder
        resubmits after connect."""
        container = cls(document_service, registry)
        # Virtualizing drivers resolve stubbed channel snapshots lazily at
        # realization (drivers/virtualized_driver.py); plain drivers have
        # no resolver and never produce stubs.
        container.snapshot_resolver = getattr(
            document_service.storage, "resolve_blob", None)
        snapshot = document_service.storage.get_latest_snapshot()
        if snapshot is not None:
            container.protocol = ProtocolOpHandler.load(snapshot["protocol"])
            container._wire_quorum()
            if code_loader is not None:
                # The quorum's committed "code" value picks the runtime
                # factory BEFORE any channel instantiates
                # (container.ts:1700-1835 instantiateRuntime).
                factory = code_loader.load(
                    container.protocol.quorum.get("code"))
                factory.instantiate(container)
            container.runtime.load(snapshot["runtime"])
            container.delta_manager.last_processed_seq = \
                snapshot["sequence_number"]
            container.delta_manager.last_queued_seq = \
                snapshot["sequence_number"]
        container.attached = True
        if pending_state is not None:
            # Stashed ops re-apply against the exact state the dead session
            # last saw: catch up to its refSeq first, then apply, then go
            # live (the rest of catch-up delivers any sequenced stashed ops
            # as acks against the stash).
            ref = pending_state["reference_sequence_number"]
            if snapshot is not None and snapshot["sequence_number"] > ref:
                raise ValueError(
                    "stash predates the latest snapshot; resume requires "
                    "deltas from the stash's reference point")
            container.delta_manager.catch_up_to(ref)
            container._apply_stashed_state(pending_state)
        container.connect(mode)
        if pending_state is not None:
            container._stashed_client_id = None
            container.runtime.replay_pending()
        return container

    @classmethod
    def create_detached(cls, document_service: DocumentService, registry=None
                        ) -> "Container":
        """Create a new (empty) document; call attach() to go live. Edits made
        while detached apply locally and ship via the attach-time snapshot."""
        return cls(document_service, registry)

    def attach(self) -> None:
        """Publish the detached state as the document's base snapshot and go
        live (container.ts attach: detached → attached lifecycle)."""
        assert not self.attached, "already attached"
        self.runtime.on_attach()
        self._service.storage.upload_snapshot(self.summarize())
        self.attached = True
        self.connect()

    def _wire_quorum(self) -> None:
        """Membership events fan out to interested channels (e.g. consensus
        queues auto-release a departed client's leases)."""
        self.protocol.quorum.on_remove_member.append(self._on_member_removed)

    def _on_member_removed(self, client_id: str) -> None:
        for datastore in self.runtime.datastores.values():
            # Lazy consensus channels must see the leave (lease release);
            # other lazy channels stay lazy.
            datastore.realize_membership_sensitive()
            for channel in datastore.channels.values():
                on_leave = getattr(channel, "on_client_leave", None)
                if on_leave is not None:
                    on_leave(client_id)

    # -- connection state machine --------------------------------------------

    @property
    def connected(self) -> bool:
        return self.delta_manager.connected

    @property
    def client_id(self) -> str | None:
        return self.delta_manager.client_id

    @property
    def last_processed_seq(self) -> int:
        return self.delta_manager.last_processed_seq

    @property
    def inbound(self):
        return self.delta_manager.inbound

    @property
    def outbound(self):
        return self.delta_manager.outbound

    def connect(self, mode: str | None = None) -> None:
        """Connect in the given mode; omitted = keep the container's mode
        (so reconnect of a read-only container stays read-only)."""
        if mode is not None:
            self._mode = mode
        client_id = self.delta_manager.connect(self._mode)
        for cb in self.on_connected:
            cb(client_id)

    def disconnect(self) -> None:
        if not self.connected:
            return
        self.delta_manager.disconnect()
        for cb in self.on_disconnected:
            cb()

    def reconnect(self) -> None:
        """Drop + re-establish the connection, replaying pending local ops
        (deltaManager.ts:566-692 + containerRuntime replayPendingStates)."""
        self.disconnect()
        self.connect()
        self.runtime.replay_pending()

    # -- outbound -------------------------------------------------------------

    def allocate_client_seq(self) -> int | None:
        return self.delta_manager.allocate_client_seq()

    def send_message(self, mtype: MessageType, contents: Any,
                     client_seq: int) -> None:
        self.delta_manager.submit(mtype, contents, client_seq)

    def submit_message(self, mtype: MessageType, contents: Any) -> int | None:
        """Stamp + send a message with no pending tracking (protocol msgs).
        Returns clientSequenceNumber, or None when not connected."""
        client_seq = self.allocate_client_seq()
        if client_seq is not None:
            self.send_message(mtype, contents, client_seq)
        return client_seq

    def propose(self, key: str, value: Any) -> None:
        self.submit_message(MessageType.PROPOSE, {"key": key, "value": value})

    def submit_signal(self, content: Any) -> None:
        """Transient broadcast: never sequenced, never durable (presence,
        cursors — container.ts submitSignal)."""
        self.delta_manager.submit_signal(content)

    # -- inbound --------------------------------------------------------------

    def _on_nack(self, nack: Any) -> None:
        self.nacks.append(nack)
        for cb in self.on_nack:
            cb(nack)

    def _process_signal(self, signal: Any) -> None:
        content = signal.get("content") if isinstance(signal, dict) else None
        # Only SERVICE-crafted audience signals (client_id None) may touch
        # the roster — a client echoing the payload shape must not spoof
        # membership, and falls through to the app like any signal.
        if (isinstance(content, dict)
                and content.get("type") == "__audience__"  # audience.py
                and signal.get("client_id") is None):
            self.audience._apply(content)
            return  # system signal, not app-visible
        for cb in self.on_signal:
            cb(signal)

    def _apply_stashed_state(self, pending_state: dict) -> None:
        """Re-apply stashed unacked ops locally and re-register them as
        pending under their ORIGINAL clientSeqNumbers."""
        self._stashed_client_id = pending_state.get("client_id")
        for item in pending_state.get("pending", []):
            envelope = item["contents"]
            if envelope.get("type") == "attach":
                if envelope["id"] not in self.runtime.datastores:
                    from .datastore import DataStoreRuntime
                    datastore = DataStoreRuntime(
                        envelope["id"], self.runtime, self.runtime.registry)
                    self.runtime.datastores[envelope["id"]] = datastore
                    datastore.load(envelope["snapshot"])
                    if envelope.get("root"):
                        self.runtime.root_datastores.add(envelope["id"])
                self.runtime.pending.on_submit(item["client_seq"],
                                               envelope, None)
                continue
            datastore = self.runtime.datastores[envelope["address"]]
            channel = datastore.get_channel(envelope["contents"]["address"])
            metadata = channel.apply_stashed_op(
                envelope["contents"]["contents"])
            self.runtime.pending.on_submit(item["client_seq"], envelope,
                                           metadata)

    def close_and_get_pending_state(self) -> dict:
        """Serialize unacked local ops for offline resume
        (container.ts closeAndGetPendingLocalState): pass the result to
        :meth:`load` as ``pending_state``. Closes the container."""
        state = {
            "client_id": self.client_id,
            "reference_sequence_number": self.last_processed_seq,
            "pending": [{"client_seq": item.client_seq,
                         "contents": item.contents}
                        for item in self.runtime.pending.drain_for_replay()],
        }
        self.close()
        return state

    def _process_remote_message(self, message: SequencedDocumentMessage) -> None:
        local = (
            self.client_id is not None and message.client_id == self.client_id
        )
        if (not local and self._stashed_client_id is not None
                and message.client_id == self._stashed_client_id
                and self.runtime.pending.has_pending):
            # An op our PREVIOUS session got sequenced before dying: ack it
            # against the re-applied stash (sequenced stashed ops are a
            # FIFO prefix of the stash — the server orders clientSeqs).
            local = True
        result = self.protocol.process_message(message, local)
        if message.type == MessageType.OPERATION:
            self.runtime.process(message, local)
        elif message.type == MessageType.ATTACH:
            self.runtime.process_attach(message, local)
        elif message.type == MessageType.CHUNKED_OP:
            self.runtime.process_chunk(message, local)
        for cb in self.on_op_processed:
            cb(message)
        if result["immediate_noop"] and self.connected:
            # Expedite proposal commit (quorum.ts:326): a contentful noop revs
            # and carries our advanced refSeq to the sequencer.
            self.submit_message(MessageType.NOOP, "")

    # -- summary --------------------------------------------------------------

    def summarize(self, unchanged_before: int | None = None) -> dict:
        """Summary of protocol + runtime state at the current seq. With
        ``unchanged_before`` (the last ACKED summary's seq), unchanged
        channels serialize as handle stubs into that summary — the
        incremental form (summary.ts:53); callers must then upload with
        the parent handle so the service can resolve the stubs."""
        return {
            "sequence_number": self.last_processed_seq,
            "protocol": self.protocol.snapshot(),
            "runtime": self.runtime.summarize(unchanged_before),
        }

    def close(self) -> None:
        self.disconnect()
