"""Client runtime: container, data stores, delta manager, pending state.

Reference parity: packages/runtime/* + packages/loader/container-loader.
"""
