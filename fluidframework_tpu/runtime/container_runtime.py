"""ContainerRuntime — container-level op router, batching, pending replay.

Reference parity: packages/runtime/container-runtime/src/containerRuntime.ts
(``ContainerRuntime``: process:1042 routing {address: dataStoreId} envelopes,
submit:1589, reSubmit:1722, replayPendingStates:989-1027) and
dataStores.ts:274.
"""

from __future__ import annotations

import json
from dataclasses import replace
from typing import TYPE_CHECKING, Any

from ..dds.shared_object import ChannelRegistry, default_registry
from ..protocol.messages import MessageType, SequencedDocumentMessage
from .blob_manager import BlobManager
from .datastore import DataStoreRuntime
from .pending_state import PendingStateManager

if TYPE_CHECKING:  # pragma: no cover
    from .container import Container

# Ops above this serialized size split into CHUNKED_OP pieces — the
# reference's 16KB alfred cap (config.json:38, containerRuntime.ts:1652).
MAX_OP_BYTES = 16 * 1024


class ContainerRuntime:
    def __init__(self, container: "Container",
                 registry: ChannelRegistry | None = None) -> None:
        self.container = container
        self.registry = registry if registry is not None else default_registry()
        self.datastores: dict[str, DataStoreRuntime] = {}
        # Root (aliased) data stores: GC-reachable from "/" even with no
        # stored handle to them (containerRuntime.ts createRootDataStore).
        self.root_datastores: set[str] = set()
        self.pending = PendingStateManager()
        self.blobs = BlobManager(self)
        self.max_op_bytes = MAX_OP_BYTES
        # In-flight chunked-op reassembly, keyed by sender client id
        # (one chunked op in flight per client, containerRuntime.ts rule).
        self._chunks: dict[str, list[str]] = {}
        # Client seqs of ops voided by a lost concurrent-create race: their
        # echoes apply as REMOTE ops (the local state they referenced was
        # replaced by the winner's snapshot) — see process_attach.
        self._voided: set[int] = set()

    # -- data store lifecycle -------------------------------------------------

    def create_datastore(self, datastore_id: str, root: bool = True,
                         attributes: dict | None = None) -> DataStoreRuntime:
        if datastore_id in self.datastores:
            raise ValueError(f"datastore {datastore_id!r} already exists")
        datastore = DataStoreRuntime(datastore_id, self, self.registry,
                                     attributes)
        self.datastores[datastore_id] = datastore
        if root:
            self.root_datastores.add(datastore_id)
        if self.container.attached:
            # Announce to peers (containerRuntime.ts attach message): the
            # snapshot ships the store's channels as of submit time; later
            # channel/DDS ops are sequenced after this and replay on top.
            self._submit_attach(datastore)
        return datastore

    def get_datastore(self, datastore_id: str) -> DataStoreRuntime:
        return self.datastores[datastore_id]

    def resolve_path(self, absolute_path: str):
        """Resolve a handle path: ``/ds`` → DataStoreRuntime,
        ``/ds/channel`` → SharedObject."""
        parts = absolute_path.strip("/").split("/")
        datastore = self.datastores[parts[0]]
        return datastore if len(parts) == 1 else \
            datastore.get_channel(parts[1])

    # -- garbage collection ---------------------------------------------------

    def run_gc(self, datastore_summaries: dict | None = None):
        """Mark-phase GC over stored handle routes (garbageCollector.ts):
        roots = every root data store. Pass already-serialized datastore
        summaries to avoid re-serializing channel state for the graph."""
        from .garbage_collector import run_garbage_collection
        graph: dict[str, list[str]] = {}
        for ds_id, datastore in self.datastores.items():
            summary = None if datastore_summaries is None else \
                datastore_summaries[ds_id]
            graph.update(datastore.get_gc_data(summary))
        roots = [f"/{ds_id}" for ds_id in sorted(self.root_datastores)]
        return run_garbage_collection(graph, roots)

    # -- outbound -------------------------------------------------------------

    def submit_datastore_op(self, datastore_id: str, contents: dict,
                            local_op_metadata: Any) -> None:
        if not self.container.attached:
            return  # detached edits ship via the attach-time snapshot
        envelope = {"address": datastore_id, "contents": contents}
        serialized = json.dumps(envelope, default=list)
        if len(serialized) > self.max_op_bytes:
            self._submit_chunked(envelope, serialized, local_op_metadata)
            return
        # Pending is recorded BEFORE the send: the in-proc server acks
        # re-entrantly. client_seq None = disconnected: the op stays pending
        # (never sent) and is replayed on reconnect (pendingStateManager.ts:56).
        client_seq = self.container.allocate_client_seq()
        self.pending.on_submit(client_seq, envelope, local_op_metadata)
        if client_seq is not None:
            self.container.send_message(
                MessageType.OPERATION, envelope, client_seq)

    def _submit_chunked(self, envelope: dict, serialized: str,
                        local_op_metadata: Any) -> None:
        """Split an oversized op into CHUNKED_OP pieces
        (containerRuntime.ts submitChunkedMessage :1652). Only the FINAL
        chunk carries the pending entry: its ack is the op's ack, and a
        reconnect replays the op whole (re-chunking on the way out)."""
        pieces = [serialized[i:i + self.max_op_bytes]
                  for i in range(0, len(serialized), self.max_op_bytes)]
        total = len(pieces)
        for index, piece in enumerate(pieces):
            final = index == total - 1
            client_seq = self.container.allocate_client_seq()
            if final:
                self.pending.on_submit(client_seq, envelope,
                                       local_op_metadata)
            if client_seq is not None:
                self.container.send_message(
                    MessageType.CHUNKED_OP,
                    {"index": index, "total": total, "data": piece},
                    client_seq)

    def process_chunk(self, message: SequencedDocumentMessage,
                      local: bool) -> None:
        """Reassemble CHUNKED_OP pieces; the final piece processes as a
        normal OPERATION at the final chunk's sequence number."""
        contents = message.contents
        assert message.client_id is not None
        buffer = self._chunks.setdefault(message.client_id, [])
        assert contents["index"] == len(buffer), "chunk disorder"
        buffer.append(contents["data"])
        if len(buffer) < contents["total"]:
            return
        envelope = json.loads("".join(self._chunks.pop(message.client_id)))
        self.process(replace(message, type=MessageType.OPERATION,
                             contents=envelope), local)

    def _submit_attach(self, datastore: DataStoreRuntime,
                       snapshot: dict | None = None) -> None:
        # The snapshot is captured at CREATE time (not resend time): any
        # state added later travels as its own pending ops, which must not
        # also be baked into a replayed attach (or remotes apply it twice).
        contents = {
            "id": datastore.id,
            "root": datastore.id in self.root_datastores,
            "snapshot": datastore.summarize() if snapshot is None
            else snapshot,
        }
        client_seq = self.container.allocate_client_seq()
        # Tracked pending like any op so a disconnected create replays on
        # reconnect; the replay marker is the "attach" type key.
        self.pending.on_submit(
            client_seq, {"type": "attach", **contents}, None)
        if client_seq is not None:
            self.container.send_message(
                MessageType.ATTACH, contents, client_seq)

    def process_attach(self, message: SequencedDocumentMessage,
                       local: bool) -> None:
        if local:
            if message.client_sequence_number in self._voided:
                # Echo of OUR losing create in a concurrent-create race:
                # the winner's snapshot was already adopted; drop it (every
                # remote replica ignores this second attach too).
                self._voided.discard(message.client_sequence_number)
            else:
                self.pending.process_own_message(
                    message.client_sequence_number)
            return
        contents = message.contents
        if contents["id"] in self.datastores:
            # Concurrent create: first sequenced attach wins the state, but
            # the root flag is the OR of all creates (commutative, so every
            # replica converges regardless of arrival order).
            if contents["root"]:
                self.root_datastores.add(contents["id"])
            # If OUR create of this id is still pending, the remote attach
            # is the first-sequenced winner: adopt its snapshot, void our
            # pending attach + ops (their echoes re-apply as remote ops so
            # all replicas process the loser's ops identically). Matches the
            # reference's alias resolution for well-known ids
            # (containerRuntime.ts createRootDataStore / alias ops).
            voided = self.pending.void_datastore(contents["id"])
            if voided:
                self._voided |= voided
                # Adopt in place: held DataStoreRuntime AND channel object
                # references stay valid, with their state reloaded from the
                # winner's snapshot (see DataStoreRuntime.adopt).
                self.datastores[contents["id"]].adopt(contents["snapshot"])
            return
        datastore = DataStoreRuntime(contents["id"], self, self.registry)
        self.datastores[contents["id"]] = datastore
        datastore.load(contents["snapshot"])
        if contents["root"]:
            self.root_datastores.add(contents["id"])

    def void_channel(self, datastore_id: str, channel_id: str) -> bool:
        """Void our pending create of a channel that lost a same-id race
        (see PendingStateManager.void_channel); True if anything voided."""
        voided = self.pending.void_channel(datastore_id, channel_id)
        self._voided |= voided
        return bool(voided)

    def void_channel_ops(self, datastore_id: str, channel_id: str) -> None:
        """Unconditionally void pending ops against a channel whose state is
        being replaced by an adopting attach_channel."""
        self._voided |= self.pending.void_channel_ops(
            datastore_id, channel_id)

    # -- inbound --------------------------------------------------------------

    def process(self, message: SequencedDocumentMessage, local: bool) -> None:
        assert message.type == MessageType.OPERATION
        local_op_metadata = None
        if local:
            if message.client_sequence_number in self._voided:
                # Own op voided by a lost create race: the channel state it
                # was submitted against is gone (replaced by the winner's
                # snapshot) — apply it as a remote op, exactly as every other
                # replica does. The sentinel tells merge engines to exclude
                # local unacked state from visibility despite the author id
                # being our own.
                from ..dds.shared_object import VOIDED_LOCAL_ECHO
                self._voided.discard(message.client_sequence_number)
                local = False
                local_op_metadata = VOIDED_LOCAL_ECHO
            else:
                local_op_metadata = self.pending.process_own_message(
                    message.client_sequence_number)
        envelope = message.contents
        datastore = self.datastores[envelope["address"]]
        datastore.process(
            replace(message, contents=envelope["contents"]),
            local,
            local_op_metadata,
        )

    # -- reconnect ------------------------------------------------------------

    def replay_pending(self) -> None:
        """Resubmit every unacked op through the owning channel so it can
        regenerate/restamp (containerRuntime.ts replayPendingStates)."""
        # Ops pending against still-unadopted channels must not replay (the
        # state they target is provisional — if their adopting
        # attach_channel was sequenced, catch-up delivers it and the old
        # ops as remote ops from our previous identity).
        for datastore in self.datastores.values():
            datastore.void_adoption_pending_ops()
        # Voided ops from a lost create race never echo across a reconnect
        # under the OLD client seqs (client seqs restart with the new
        # connection) — clear so stale entries can't void fresh ops.
        self._voided.clear()
        for item in self.pending.drain_for_replay():
            envelope = item.contents
            if envelope.get("type") == "attach":
                # Re-announce with the ORIGINAL create-time snapshot; the
                # state added since rides the pending ops replayed after us.
                self._submit_attach(self.datastores[envelope["id"]],
                                    snapshot=envelope["snapshot"])
                continue
            datastore = self.datastores[envelope["address"]]
            datastore.resubmit(envelope["contents"], item.local_op_metadata)

    def on_attach(self) -> None:
        for datastore in self.datastores.values():
            for channel in datastore.channels.values():
                channel.on_attach()
        self.blobs.on_attach()

    # -- summary --------------------------------------------------------------

    def summarize(self, unchanged_before: int | None = None) -> dict:
        datastores = {
            datastore_id: datastore.summarize(unchanged_before)
            for datastore_id, datastore in sorted(self.datastores.items())
        }
        gc = self.run_gc(datastores)
        return {
            "datastores": datastores,
            "roots": sorted(self.root_datastores),
            "blobs": self.blobs.summarize(),
            # GC state rides the summary (containerRuntime.ts:1383-1430);
            # unreferenced nodes are reported, not yet swept.
            "gc": {"unreferenced": gc.deleted},
        }

    def load(self, snapshot: dict) -> None:
        for datastore_id, datastore_snapshot in snapshot["datastores"].items():
            datastore = DataStoreRuntime(datastore_id, self, self.registry)
            self.datastores[datastore_id] = datastore
            datastore.load(datastore_snapshot)
        self.root_datastores = set(
            snapshot.get("roots", snapshot["datastores"].keys()))
        self.blobs.load(snapshot.get("blobs"))
