"""ContainerRuntime — container-level op router, batching, pending replay.

Reference parity: packages/runtime/container-runtime/src/containerRuntime.ts
(``ContainerRuntime``: process:1042 routing {address: dataStoreId} envelopes,
submit:1589, reSubmit:1722, replayPendingStates:989-1027) and
dataStores.ts:274.
"""

from __future__ import annotations

from dataclasses import replace
from typing import TYPE_CHECKING, Any

from ..dds.shared_object import ChannelRegistry, default_registry
from ..protocol.messages import MessageType, SequencedDocumentMessage
from .datastore import DataStoreRuntime
from .pending_state import PendingStateManager

if TYPE_CHECKING:  # pragma: no cover
    from .container import Container


class ContainerRuntime:
    def __init__(self, container: "Container",
                 registry: ChannelRegistry | None = None) -> None:
        self.container = container
        self.registry = registry if registry is not None else default_registry()
        self.datastores: dict[str, DataStoreRuntime] = {}
        self.pending = PendingStateManager()

    # -- data store lifecycle -------------------------------------------------

    def create_datastore(self, datastore_id: str) -> DataStoreRuntime:
        if datastore_id in self.datastores:
            raise ValueError(f"datastore {datastore_id!r} already exists")
        datastore = DataStoreRuntime(datastore_id, self, self.registry)
        self.datastores[datastore_id] = datastore
        return datastore

    def get_datastore(self, datastore_id: str) -> DataStoreRuntime:
        return self.datastores[datastore_id]

    # -- outbound -------------------------------------------------------------

    def submit_datastore_op(self, datastore_id: str, contents: dict,
                            local_op_metadata: Any) -> None:
        if not self.container.attached:
            return  # detached edits ship via the attach-time snapshot
        envelope = {"address": datastore_id, "contents": contents}
        # Pending is recorded BEFORE the send: the in-proc server acks
        # re-entrantly. client_seq None = disconnected: the op stays pending
        # (never sent) and is replayed on reconnect (pendingStateManager.ts:56).
        client_seq = self.container.allocate_client_seq()
        self.pending.on_submit(client_seq, envelope, local_op_metadata)
        if client_seq is not None:
            self.container.send_message(
                MessageType.OPERATION, envelope, client_seq)

    # -- inbound --------------------------------------------------------------

    def process(self, message: SequencedDocumentMessage, local: bool) -> None:
        assert message.type == MessageType.OPERATION
        local_op_metadata = None
        if local:
            local_op_metadata = self.pending.process_own_message(
                message.client_sequence_number)
        envelope = message.contents
        datastore = self.datastores[envelope["address"]]
        datastore.process(
            replace(message, contents=envelope["contents"]),
            local,
            local_op_metadata,
        )

    # -- reconnect ------------------------------------------------------------

    def replay_pending(self) -> None:
        """Resubmit every unacked op through the owning channel so it can
        regenerate/restamp (containerRuntime.ts replayPendingStates)."""
        for item in self.pending.drain_for_replay():
            envelope = item.contents
            datastore = self.datastores[envelope["address"]]
            datastore.resubmit(envelope["contents"], item.local_op_metadata)

    def on_attach(self) -> None:
        for datastore in self.datastores.values():
            for channel in datastore.channels.values():
                channel.on_attach()

    # -- summary --------------------------------------------------------------

    def summarize(self) -> dict:
        return {
            "datastores": {
                datastore_id: datastore.summarize()
                for datastore_id, datastore in sorted(self.datastores.items())
            }
        }

    def load(self, snapshot: dict) -> None:
        for datastore_id, datastore_snapshot in snapshot["datastores"].items():
            datastore = DataStoreRuntime(datastore_id, self, self.registry)
            self.datastores[datastore_id] = datastore
            datastore.load(datastore_snapshot)
