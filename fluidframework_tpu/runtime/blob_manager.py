"""BlobManager — out-of-band attachment blobs referenced by handle.

Reference parity: packages/runtime/container-runtime/src/blobManager.ts:51
— large binary payloads (images, file attachments) never ride the op
stream; they upload straight to storage and DDS values carry only the
handle path (``/_blobs/<id>``). The redirect table of known blob ids
rides the summary so GC and late joiners see them.
"""

from __future__ import annotations

import hashlib
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from .container_runtime import ContainerRuntime

BLOB_PATH_PREFIX = "/_blobs/"


class BlobHandle:
    """Handle to an uploaded blob; serializes as its absolute path (the
    shared-object handle rule, handles.py)."""

    def __init__(self, runtime: "ContainerRuntime", blob_id: str) -> None:
        self._runtime = runtime
        self.blob_id = blob_id
        self.absolute_path = BLOB_PATH_PREFIX + blob_id

    def get(self) -> bytes:
        return self._runtime.blobs.read(self.blob_id)

    def __eq__(self, other) -> bool:
        return isinstance(other, BlobHandle) and \
            other.absolute_path == self.absolute_path

    def __hash__(self) -> int:
        return hash(self.absolute_path)


class BlobManager:
    def __init__(self, runtime: "ContainerRuntime") -> None:
        self._runtime = runtime
        # Detached-phase blobs buffer locally and upload at attach
        # (blobManager.ts offline/detached flow).
        self._detached: dict[str, bytes] = {}
        # Ids we know exist in storage (uploaded here or seen in a summary).
        self._known: set[str] = set()

    def upload_blob(self, data: bytes) -> BlobHandle:
        blob_id = hashlib.sha256(data).hexdigest()
        if self._runtime.container.attached:
            self._storage().create_blob(blob_id, data)
        else:
            self._detached[blob_id] = data
        self._known.add(blob_id)
        return BlobHandle(self._runtime, blob_id)

    def read(self, blob_id: str) -> bytes:
        if blob_id in self._detached:
            return self._detached[blob_id]
        return self._storage().read_blob(blob_id)

    def get_handle(self, blob_id: str) -> BlobHandle:
        return BlobHandle(self._runtime, blob_id)

    def on_attach(self) -> None:
        for blob_id, data in self._detached.items():
            self._storage().create_blob(blob_id, data)
        self._detached.clear()

    def _storage(self):
        return self._runtime.container._service.storage

    # -- summary ---------------------------------------------------------------

    def summarize(self) -> dict:
        return {"ids": sorted(self._known)}

    def load(self, content: dict | None) -> None:
        self._known = set((content or {}).get("ids", []))