"""Reference-graph garbage collection over handle routes.

Reference parity: packages/runtime/garbage-collector/src/garbageCollector.ts
(``runGarbageCollection``: mark reachable from the root over the node →
outbound-routes graph, report referenced/deleted) and utils.ts:90
(``GCDataBuilder`` route normalization). The graph nodes are data stores
(``/ds``) and channels (``/ds/channel``); edges are stored handles
(see :mod:`.handles`) plus the implicit datastore→its-channels edges.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class GCResult:
    referenced: list[str] = field(default_factory=list)
    deleted: list[str] = field(default_factory=list)  # unreachable nodes


def normalize_route(route: str) -> str:
    """Strip trailing slash; routes are ``/ds`` or ``/ds/channel``."""
    return route.rstrip("/") if route != "/" else route


def run_garbage_collection(graph: dict[str, list[str]],
                           roots: list[str]) -> GCResult:
    """Mark-phase BFS from ``roots`` over ``graph`` (node → outbound routes).

    Referencing any node also references its ancestors' children? No — per
    the reference, referencing ``/ds/channel`` references ``/ds`` (a channel
    cannot outlive its store), and referencing ``/ds`` references all of its
    channels via the implicit edges the caller includes in ``graph``.
    """
    reachable: set[str] = set()
    stack = [normalize_route(r) for r in roots]
    while stack:
        node = stack.pop()
        if node in reachable:
            continue
        reachable.add(node)
        # /ds/channel keeps /ds alive (garbageCollector.ts parent routes).
        if node.count("/") >= 2:
            parent = node.rsplit("/", 1)[0]
            if parent not in reachable:
                stack.append(parent)
        for route in graph.get(node, ()):  # outbound handle edges
            route = normalize_route(route)
            if route not in reachable:
                stack.append(route)
    all_nodes = set(graph.keys())
    return GCResult(
        referenced=sorted(n for n in all_nodes if n in reachable),
        deleted=sorted(n for n in all_nodes if n not in reachable),
    )
