"""PendingStateManager — tracks unacked local ops for ack matching + replay.

Reference parity: packages/runtime/container-runtime/src/
pendingStateManager.ts:56 — local ops are enqueued at submit with their
localOpMetadata; when the server echoes our op back (same clientId), the
front of the queue must match by clientSequenceNumber and yields the metadata
for the local apply; on reconnect the whole queue is replayed through
``ContainerRuntime.reSubmit`` (containerRuntime.ts:989-1027).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any


@dataclass(frozen=True, slots=True)
class PendingMessage:
    client_seq: int
    contents: Any
    local_op_metadata: Any


class PendingStateManager:
    def __init__(self) -> None:
        self._pending: deque[PendingMessage] = deque()

    def __len__(self) -> int:
        return len(self._pending)

    @property
    def has_pending(self) -> bool:
        return bool(self._pending)

    def on_submit(self, client_seq: int, contents: Any,
                  local_op_metadata: Any) -> None:
        self._pending.append(
            PendingMessage(client_seq, contents, local_op_metadata))

    def process_own_message(self, client_seq: int) -> Any:
        """Pop the matching pending entry; returns its localOpMetadata."""
        assert self._pending, "ack for an op we never submitted"
        front = self._pending.popleft()
        assert front.client_seq == client_seq, (
            f"unordered ack: expected clientSeq {front.client_seq}, "
            f"got {client_seq}"
        )
        return front.local_op_metadata

    def _void_matching(self, guard, target) -> set[int]:
        """If any pending entry satisfies ``guard``, remove every entry
        satisfying ``target`` and return their client seqs (else void
        nothing). The voided ops' echoes are applied as remote ops by the
        runtime — see ContainerRuntime._voided."""
        if not any(guard(item.contents) for item in self._pending):
            return set()
        voided: set[int] = set()
        kept: deque[PendingMessage] = deque()
        for item in self._pending:
            if target(item.contents):
                voided.add(item.client_seq)
            else:
                kept.append(item)
        self._pending = kept
        return voided

    @staticmethod
    def _is_datastore_attach(contents: Any, datastore_id: str) -> bool:
        return (contents.get("type") == "attach"
                and contents.get("id") == datastore_id)

    @staticmethod
    def _targets_channel(contents: Any, datastore_id: str,
                         channel_id: str) -> bool:
        if contents.get("type") == "attach":
            return False
        if contents.get("address") != datastore_id:
            return False
        inner = contents.get("contents")
        return isinstance(inner, dict) and inner.get("address") == channel_id

    @staticmethod
    def _is_channel_attach(contents: Any, datastore_id: str,
                           channel_id: str) -> bool:
        return (PendingStateManager._targets_channel(
                    contents, datastore_id, channel_id)
                and contents["contents"].get("type") == "attach_channel")

    def void_datastore(self, datastore_id: str) -> set[int]:
        """If our CREATE (attach) of this data store is still pending, a
        concurrent remote create won the sequencing race: remove the pending
        attach plus every pending op addressed to the store and return their
        client seqs. The runtime replaces the local state with the winner's
        snapshot and, when the voided ops echo back, applies them as remote
        ops (every replica applies them to the winner's state the same way).
        No pending attach → not a race loss (our earlier attach already won)
        → nothing is voided."""
        return self._void_matching(
            lambda c: self._is_datastore_attach(c, datastore_id),
            lambda c: self._is_datastore_attach(c, datastore_id)
            or (c.get("type") != "attach"
                and c.get("address") == datastore_id))

    def void_channel(self, datastore_id: str, channel_id: str) -> set[int]:
        """Channel-level analog of void_datastore: if our CREATE
        (attach_channel) of this channel is still pending, a concurrent
        remote create of the same channel id won the race — void our
        pending attach_channel plus every pending op addressed to the
        channel and return their client seqs."""
        return self._void_matching(
            lambda c: self._is_channel_attach(c, datastore_id, channel_id),
            lambda c: self._targets_channel(c, datastore_id, channel_id))

    def void_channel_ops(self, datastore_id: str, channel_id: str) -> set[int]:
        """Unconditionally void every pending op addressed to the channel
        (no pending-attach guard): used when a channel's state is reloaded
        by an adopting attach_channel — ops recorded against the pre-adopt
        state must echo as remote ops, not local acks."""
        return self._void_matching(
            lambda _c: True,
            lambda c: self._targets_channel(c, datastore_id, channel_id))

    def drain_for_replay(self) -> list[PendingMessage]:
        """Take everything pending (reconnect replay). Queue is emptied; the
        replay re-submits and re-enqueues with fresh client seq numbers."""
        items = list(self._pending)
        self._pending.clear()
        return items
