"""PendingStateManager — tracks unacked local ops for ack matching + replay.

Reference parity: packages/runtime/container-runtime/src/
pendingStateManager.ts:56 — local ops are enqueued at submit with their
localOpMetadata; when the server echoes our op back (same clientId), the
front of the queue must match by clientSequenceNumber and yields the metadata
for the local apply; on reconnect the whole queue is replayed through
``ContainerRuntime.reSubmit`` (containerRuntime.ts:989-1027).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any


@dataclass(frozen=True, slots=True)
class PendingMessage:
    client_seq: int
    contents: Any
    local_op_metadata: Any


class PendingStateManager:
    def __init__(self) -> None:
        self._pending: deque[PendingMessage] = deque()

    def __len__(self) -> int:
        return len(self._pending)

    @property
    def has_pending(self) -> bool:
        return bool(self._pending)

    def on_submit(self, client_seq: int, contents: Any,
                  local_op_metadata: Any) -> None:
        self._pending.append(
            PendingMessage(client_seq, contents, local_op_metadata))

    def process_own_message(self, client_seq: int) -> Any:
        """Pop the matching pending entry; returns its localOpMetadata."""
        assert self._pending, "ack for an op we never submitted"
        front = self._pending.popleft()
        assert front.client_seq == client_seq, (
            f"unordered ack: expected clientSeq {front.client_seq}, "
            f"got {client_seq}"
        )
        return front.local_op_metadata

    def drain_for_replay(self) -> list[PendingMessage]:
        """Take everything pending (reconnect replay). Queue is emptied; the
        replay re-submits and re-enqueues with fresh client seq numbers."""
        items = list(self._pending)
        self._pending.clear()
        return items
