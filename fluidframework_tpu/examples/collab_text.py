"""CollabText — a collaborative text editor example app.

Reference parity: examples/data-objects/shared-text — a DataObject whose
document body is a SharedString; concurrent edits from any number of
clients converge through the merge-tree, annotations style ranges (bold
here), and an interval collection tracks a shared "comment" range that
rides the text through remote edits.

Run:  python -m fluidframework_tpu.examples.collab_text
"""

from __future__ import annotations

from ..dds.sequence import SharedString
from ..framework.data_object import DataObject
from ..framework.data_object_factory import DataObjectFactory

TEXT_ID = "body"
COMMENTS_LABEL = "comments"


class CollabText(DataObject):
    def initializing_first_time(self, props=None) -> None:
        text = self.runtime.create_channel(
            TEXT_ID, SharedString.channel_type)
        self.root.set(TEXT_ID, text.handle)
        if props and props.get("initial_text"):
            text.insert_text(0, props["initial_text"])

    @property
    def text(self) -> SharedString:
        return self.root.get(TEXT_ID).get()

    # -- editor operations ----------------------------------------------------

    def type_text(self, pos: int, text: str) -> None:
        self.text.insert_text(pos, text)

    def delete(self, start: int, end: int) -> None:
        self.text.remove_text(start, end)

    def bold(self, start: int, end: int) -> None:
        self.text.annotate_range(start, end, {"bold": True})

    def comment(self, start: int, end: int, note: str) -> None:
        """Attach a note to a range; the interval follows the text."""
        self.text.get_interval_collection(COMMENTS_LABEL).add(
            start, end, props={"note": note})

    def comments(self) -> list[tuple[int, int, str]]:
        collection = self.text.get_interval_collection(COMMENTS_LABEL)
        return sorted((start, end, (props or {}).get("note"))
                      for start, end, props
                      in collection.resolved().values())

    def read(self) -> str:
        return self.text.get_text()


collab_text_factory = DataObjectFactory("collab-text", CollabText)


def main(argv: list[str] | None = None) -> None:
    import argparse

    from .host import open_document, parse_endpoint_args

    parser = argparse.ArgumentParser(description=__doc__)
    parse_endpoint_args(parser)
    args = parser.parse_args(argv)

    with open_document("collab-text", args,
                       props={"initial_text": "hello"}) as session:
        creator, joiner = session.creator, session.joiner
        joiner.type_text(len(joiner.read()), " world")
        creator.type_text(0, "doc: ")
        session.settle()
        creator.bold(0, 4)
        joiner.comment(5, 10, "greeting")
        session.settle()
        print(f"collab_text: {creator.read()!r} == {joiner.read()!r}, "
              f"comments={joiner.comments()}")
        assert creator.read() == joiner.read()
        if session.created:
            assert creator.read() == "doc: hello world"


if __name__ == "__main__":
    main()
