"""RichTextEditor — the editor-grade shared-text example app.

Reference parity: examples/data-objects/shared-text/src (the reference's
flagship rich-text app class, plus the webflow/prosemirror-style document
model): a SharedString holds marker-structured paragraphs, formatting
annotates style character ranges, and interval collections carry comments
that ride the text through concurrent remote edits. This is the shape
that stresses annotate planes, markers and interval rebinds TOGETHER —
the gap called out in VERDICT r4 ("Editor-grade example").

Document model
--------------
* Every paragraph is opened by a ``paragraph`` Marker carrying an id;
  paragraph text is the run of characters after its marker up to the
  next marker. An empty document has one initial paragraph.
* Formatting ops annotate arbitrary character ranges with LWW props
  (``bold``/``em``/``font``); removing formatting writes ``None``.
* Comments live in an interval collection: a comment anchors to a
  character range and follows it as concurrent inserts/removes shift,
  split, or slide the underlying segments.
* ``render()`` returns the structured document — paragraphs of styled
  runs with their comments — byte-identical across converged replicas.

Run:  python -m fluidframework_tpu.examples.rich_text_editor
"""

from __future__ import annotations

import itertools

from ..dds.mergetree import Marker
from ..dds.sequence import SharedString
from ..framework.data_object import DataObject
from ..framework.data_object_factory import DataObjectFactory

TEXT_ID = "body"
COMMENTS_LABEL = "comments"
PARAGRAPH = "paragraph"

_ids = itertools.count()


class RichTextEditor(DataObject):
    """A collaborative rich-text document (paragraphs, styles, comments)."""

    def initializing_first_time(self, props=None) -> None:
        text = self.runtime.create_channel(
            TEXT_ID, SharedString.channel_type)
        self.root.set(TEXT_ID, text.handle)
        text.insert_marker(0, PARAGRAPH, self._new_paragraph_id())
        if props and props.get("initial_text"):
            text.insert_text(1, props["initial_text"])

    @property
    def text(self) -> SharedString:
        return self.root.get(TEXT_ID).get()

    def _new_paragraph_id(self) -> str:
        import uuid

        return f"p-{uuid.uuid4().hex[:8]}-{next(_ids)}"

    # -- structure -------------------------------------------------------------

    def split_paragraph(self, pos: int) -> str:
        """Press Enter at ``pos``: a new paragraph marker lands there."""
        pid = self._new_paragraph_id()
        self.text.insert_marker(pos, PARAGRAPH, pid)
        return pid

    def paragraphs(self) -> list[tuple[str, int]]:
        """(paragraph id, start position) in document order."""
        engine = self.text.engine
        out = []
        pos = 0
        for seg in engine.segments:
            vis = engine._vis_len(seg, engine.current_seq,
                                  engine.local_client)
            if vis and seg.is_marker and seg.content.ref_type == PARAGRAPH:
                out.append((seg.content.id, pos))
            pos += vis
        return out

    # -- editing ---------------------------------------------------------------

    def type_text(self, pos: int, text: str,
                  props: dict | None = None) -> None:
        self.text.insert_text(pos, text, props)

    def delete(self, start: int, end: int) -> None:
        self.text.remove_text(start, end)

    # -- formatting ------------------------------------------------------------

    def set_format(self, start: int, end: int, **styles) -> None:
        """Apply LWW formatting to [start, end): bold=True, em=True,
        font="mono", ...; a value of None removes the key."""
        self.text.annotate_range(start, end, dict(styles))

    def clear_format(self, start: int, end: int, *keys: str) -> None:
        self.text.annotate_range(start, end, {k: None for k in keys})

    # -- comments --------------------------------------------------------------

    def add_comment(self, start: int, end: int, note: str,
                    author: str | None = None) -> str:
        collection = self.text.get_interval_collection(COMMENTS_LABEL)
        interval = collection.add(start, end, props={
            "note": note,
            "author": author or self.text.engine.local_client})
        return interval.id

    def resolve_comment(self, comment_id: str) -> None:
        self.text.get_interval_collection(COMMENTS_LABEL).delete(
            comment_id)

    def comments_overlapping(self, start: int,
                             end: int) -> list[tuple[int, int, str]]:
        collection = self.text.get_interval_collection(COMMENTS_LABEL)
        out = []
        for interval in collection.find_overlapping_intervals(start, end):
            s, e, props = collection.resolved()[interval.id]
            out.append((s, e, (props or {}).get("note")))
        return sorted(out)

    # -- rendering -------------------------------------------------------------

    def render(self) -> list[dict]:
        """The structured document: one dict per paragraph with its
        styled runs and the comments anchored inside it. Converged
        replicas render identically (scenario tests assert equality)."""
        engine = self.text.engine
        collection = self.text.get_interval_collection(COMMENTS_LABEL)
        resolved = sorted(
            (s, e, (props or {}).get("note"))
            for s, e, props in collection.resolved().values())
        paragraphs: list[dict] = []
        current: dict | None = None
        pos = 0
        for seg in engine.segments:
            vis = engine._vis_len(seg, engine.current_seq,
                                  engine.local_client)
            if not vis:
                continue
            if seg.is_marker:
                if seg.content.ref_type == PARAGRAPH:
                    current = {"id": seg.content.id, "start": pos,
                               "runs": [], "comments": []}
                    paragraphs.append(current)
                pos += vis
                continue
            style = {k: v for k, v in (seg.props or {}).items()
                     if v is not None}
            if current is None:  # text before the first marker
                current = {"id": "p-implicit", "start": 0,
                           "runs": [], "comments": []}
                paragraphs.append(current)
            runs = current["runs"]
            key = tuple(sorted(style.items()))
            if runs and runs[-1][1] == key:
                runs[-1] = (runs[-1][0] + seg.content, key)
            else:
                runs.append((seg.content, key))
            pos += vis
        # Attach comments to the paragraph containing their start.
        for start, end, note in resolved:
            owner = None
            for para in paragraphs:
                if para["start"] <= start:
                    owner = para
                else:
                    break
            if owner is not None:
                owner["comments"].append((start, end, note))
        for para in paragraphs:
            para["runs"] = [(text, dict(style))
                            for text, style in para["runs"]]
        return paragraphs

    def read(self) -> str:
        return self.text.get_text()


rich_text_editor_factory = DataObjectFactory(
    "rich-text-editor", RichTextEditor)


def main(argv: list[str] | None = None) -> None:
    import argparse

    from .host import open_document, parse_endpoint_args

    parser = argparse.ArgumentParser(description=__doc__)
    parse_endpoint_args(parser)
    args = parser.parse_args(argv)

    with open_document("rich-text-editor", args,
                       props={"initial_text": "Rich text on TPU."}) \
            as session:
        creator, joiner = session.creator, session.joiner
        creator.set_format(1, 10, bold=True)
        joiner.split_paragraph(len(joiner.read()))
        joiner.type_text(len(joiner.read()), "Second paragraph.")
        session.settle()
        creator.add_comment(1, 10, "strong opener")
        joiner.set_format(1, 5, em=True, font="serif")
        session.settle()
        assert creator.render() == joiner.render()
        for para in creator.render():
            print(f"rich_text_editor: {para}")


if __name__ == "__main__":
    main()
