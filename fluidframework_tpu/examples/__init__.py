"""Example applications built on the framework API (SURVEY layer 6).

Reference parity: examples/data-objects/* — 30 sample apps demonstrating
the app programming model; the three here cover the archetypes:

  * :mod:`.clicker` — the counter app (examples/data-objects/clicker,
    BASELINE config 1's smoke workload);
  * :mod:`.collab_text` — a collaborative text editor on SharedString
    with annotations and undo (examples/data-objects/shared-text);
  * :mod:`.task_board` — a task board using a SharedDirectory of tasks
    plus a ConsensusQueue for exactly-once work claiming
    (examples/data-objects/task-selection shape).

:mod:`.host` is the base-host analog: a code-loader registry mapping
package names to these apps, loaded through the quorum code proposal.
Each example module is runnable:  python -m fluidframework_tpu.examples.clicker

Exports resolve lazily so ``python -m`` can execute a submodule as
__main__ without the package import creating a second copy of it.
"""

_EXPORTS = {
    "Clicker": "clicker", "clicker_factory": "clicker",
    "CollabText": "collab_text", "collab_text_factory": "collab_text",
    "RichTextEditor": "rich_text_editor",
    "rich_text_editor_factory": "rich_text_editor",
    "TaskBoard": "task_board", "task_board_factory": "task_board",
}

__all__ = list(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib
        module = importlib.import_module(f".{_EXPORTS[name]}", __name__)
        return getattr(module, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
