"""Whiteboard — shared freehand drawing with sticky notes.

Reference parity: examples/data-objects/canvas (an Ink-backed drawing
surface) plus the sticky-note board shape of examples/data-objects/
board (SharedMap of positioned notes, LWW per note field). Strokes are
append-only Ink streams (ink/src/ink.ts:105 semantics: create-stroke +
append-point ops commute into the same picture on every replica); notes
are a SharedDirectory keyed by note id.

Run:  python -m fluidframework_tpu.examples.whiteboard
"""

from __future__ import annotations

from ..dds.directory import SharedDirectory
from ..dds.ink import Ink
from ..framework.data_object import DataObject
from ..framework.data_object_factory import DataObjectFactory

CANVAS_ID = "canvas"
NOTES_ID = "notes"


class Whiteboard(DataObject):
    def initializing_first_time(self, props=None) -> None:
        canvas = self.runtime.create_channel(CANVAS_ID, Ink.channel_type)
        notes = self.runtime.create_channel(
            NOTES_ID, SharedDirectory.channel_type)
        self.root.set(CANVAS_ID, canvas.handle)
        self.root.set(NOTES_ID, notes.handle)

    @property
    def canvas(self) -> Ink:
        return self.root.get(CANVAS_ID).get()

    @property
    def notes(self) -> SharedDirectory:
        return self.root.get(NOTES_ID).get()

    # -- drawing ---------------------------------------------------------------

    def draw(self, points: list[tuple[float, float]],
             color: str = "black", width: int = 2) -> str:
        """One pen stroke through the given points."""
        stroke_id = self.canvas.create_stroke(
            {"color": color, "thickness": width})
        for t, (x, y) in enumerate(points):
            self.canvas.append_point(stroke_id, x, y, time_ms=t)
        return stroke_id

    def picture(self) -> dict[str, dict]:
        """Every stroke with its pen and point list (converged view)."""
        return {sid: self.canvas.get_stroke(sid)
                for sid in sorted(self.canvas.strokes)}

    # -- sticky notes ----------------------------------------------------------

    def add_note(self, note_id: str, text: str, x: int, y: int) -> None:
        sub = self.notes.create_sub_directory(note_id)
        sub.set("text", text)
        sub.set("x", x)
        sub.set("y", y)

    def move_note(self, note_id: str, x: int, y: int) -> None:
        sub = self.notes.get_sub_directory(note_id)
        sub.set("x", x)
        sub.set("y", y)

    def board(self) -> dict[str, dict]:
        out = {}
        for note_id in sorted(self.notes.root.subdirectories()):
            sub = self.notes.get_sub_directory(note_id)
            out[note_id] = {"text": sub.get("text"),
                            "x": sub.get("x"), "y": sub.get("y")}
        return out


whiteboard_factory = DataObjectFactory("whiteboard", Whiteboard)


def main(argv=None) -> None:
    import argparse

    from .host import open_document, parse_endpoint_args

    parser = argparse.ArgumentParser(description=__doc__)
    parse_endpoint_args(parser)
    args = parser.parse_args(argv)

    with open_document("whiteboard", args) as session:
        creator, joiner, settle = session
        creator.draw([(0, 0), (5, 5), (10, 0)], color="red")
        joiner.draw([(2, 2), (2, 8)], color="blue", width=4)
        creator.add_note("n1", "ship it", 10, 20)
        settle()  # the joiner must see the note before moving it
        joiner.move_note("n1", 30, 40)
        settle()
        assert creator.picture() == joiner.picture()
        assert len(creator.picture()) == 2
        assert creator.board() == joiner.board()
        assert creator.board()["n1"]["x"] == 30
        print(f"whiteboard: {len(creator.picture())} strokes, "
              f"notes={creator.board()}")


if __name__ == "__main__":
    main()
