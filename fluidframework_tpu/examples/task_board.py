"""TaskBoard — shared task list with exactly-once work claiming.

Reference parity: examples/data-objects/task-selection (+ the
ordered-collection DDS's acquire/complete contract): tasks live in a
SharedDirectory (one subdirectory per task, LWW fields); a ConsensusQueue
distributes "do this task" work items so exactly one client claims each,
no matter how many race (consensusOrderedCollection.ts:98 semantics).

Run:  python -m fluidframework_tpu.examples.task_board
"""

from __future__ import annotations

from ..dds.directory import SharedDirectory
from ..dds.ordered_collection import ConsensusQueue
from ..framework.data_object import DataObject
from ..framework.data_object_factory import DataObjectFactory

TASKS_ID = "tasks"
WORK_ID = "work"


class TaskBoard(DataObject):
    def initializing_first_time(self, props=None) -> None:
        tasks = self.runtime.create_channel(
            TASKS_ID, SharedDirectory.channel_type)
        work = self.runtime.create_channel(WORK_ID, ConsensusQueue.channel_type)
        self.root.set(TASKS_ID, tasks.handle)
        self.root.set(WORK_ID, work.handle)

    @property
    def tasks(self) -> SharedDirectory:
        return self.root.get(TASKS_ID).get()

    @property
    def work(self) -> ConsensusQueue:
        return self.root.get(WORK_ID).get()

    # -- board operations ------------------------------------------------------

    def add_task(self, task_id: str, title: str) -> None:
        sub = self.tasks.create_sub_directory(task_id)
        sub.set("title", title)
        sub.set("done", False)
        self.work.add(task_id)

    def claim_next(self) -> None:
        """Race to acquire the next work item; the sequencer arbitrates."""
        self.work.acquire()

    def claimed(self) -> dict[str, str]:
        """Work items this client currently holds: {item_id: task_id}."""
        return dict(self.work.acquired_items())

    def complete(self, item_id: str, task_id: str) -> None:
        self.tasks.get_sub_directory(task_id).set("done", True)
        self.work.complete(item_id)

    def board(self) -> dict[str, dict]:
        tasks = self.tasks
        return {name: {
            "title": tasks.get_sub_directory(name).get("title"),
            "done": tasks.get_sub_directory(name).get("done"),
        } for name in sorted(tasks.root.subdirectories())}


task_board_factory = DataObjectFactory("task-board", TaskBoard)


def main(argv: list[str] | None = None) -> None:
    import argparse

    from .host import open_document, parse_endpoint_args

    parser = argparse.ArgumentParser(description=__doc__)
    parse_endpoint_args(parser)
    args = parser.parse_args(argv)

    with open_document("task-board", args) as session:
        creator, joiner, settle = session
        creator.add_task("t1", "write docs")
        creator.add_task("t2", "fix bug")
        settle()
        # Both clients race for work; consensus hands each item to exactly
        # one of them.
        creator.claim_next()
        joiner.claim_next()
        settle()
        claims = {**{k: ("creator", v) for k, v in creator.claimed().items()},
                  **{k: ("joiner", v) for k, v in joiner.claimed().items()}}
        assert len(claims) == 2, claims
        for item_id, (who, task_id) in claims.items():
            owner = creator if who == "creator" else joiner
            owner.complete(item_id, task_id)
        settle()
        print(f"task_board: {creator.board()}")
        assert all(t["done"] for t in creator.board().values())
        assert creator.board() == joiner.board()


if __name__ == "__main__":
    main()
