"""Clicker — the canonical counter example app.

Reference parity: examples/data-objects/clicker/src/index.tsx — a
DataObject holding a SharedCounter under its root directory; every client
clicking increments the same counter and all replicas converge. This is
BASELINE config 1's smoke workload (clicker on tinylicious).

Run two simulated clients against an in-process service:

    python -m fluidframework_tpu.examples.clicker

or against a running alfred front door (the tinylicious analog):

    python -m fluidframework_tpu.server.alfred --port 7070 &
    python -m fluidframework_tpu.examples.clicker --port 7070
"""

from __future__ import annotations

from ..dds.counter import SharedCounter
from ..framework.data_object import DataObject
from ..framework.data_object_factory import DataObjectFactory

COUNTER_ID = "clicks"


class Clicker(DataObject):
    """Counter on the root directory (clicker's counterKey pattern)."""

    def initializing_first_time(self, props=None) -> None:
        counter = self.runtime.create_channel(
            COUNTER_ID, SharedCounter.channel_type)
        self.root.set(COUNTER_ID, counter.handle)

    @property
    def counter(self) -> SharedCounter:
        return self.root.get(COUNTER_ID).get()

    def click(self, times: int = 1) -> None:
        for _ in range(times):
            self.counter.increment()

    @property
    def value(self) -> int:
        return self.counter.value


clicker_factory = DataObjectFactory("clicker", Clicker)


def main(argv: list[str] | None = None) -> None:
    import argparse

    from .host import open_document, parse_endpoint_args

    parser = argparse.ArgumentParser(description=__doc__)
    parse_endpoint_args(parser)
    parser.add_argument("--clicks", type=int, default=5)
    args = parser.parse_args(argv)

    with open_document("clicker", args) as session:
        creator, joiner = session.creator, session.joiner
        before = creator.value
        creator.click(args.clicks)
        joiner.click(args.clicks)
        session.settle()
        print(f"clicker: creator sees {creator.value}, "
              f"joiner sees {joiner.value}")
        assert creator.value == joiner.value == before + 2 * args.clicks


if __name__ == "__main__":
    main()
