"""DiceRoller — the canonical hello-world data object.

Reference parity: the dice-roller sample shape (a one-key SharedMap on
the root; every client sees the same roll): the smallest possible
DataObject demonstrating create/load, LWW state and change events.

Run:  python -m fluidframework_tpu.examples.dice_roller
"""

from __future__ import annotations

import random

from ..framework.data_object import DataObject
from ..framework.data_object_factory import DataObjectFactory

DICE_KEY = "diceValue"


class DiceRoller(DataObject):
    def initializing_first_time(self, props=None) -> None:
        self.root.set(DICE_KEY, 1)

    def roll(self, rng: random.Random | None = None) -> int:
        value = (rng or random).randint(1, 6)
        self.root.set(DICE_KEY, value)
        return value

    @property
    def value(self) -> int:
        return self.root.get(DICE_KEY)


dice_roller_factory = DataObjectFactory("dice-roller", DiceRoller)


def main(argv=None) -> None:
    import argparse

    from .host import open_document, parse_endpoint_args

    parser = argparse.ArgumentParser(description=__doc__)
    parse_endpoint_args(parser)
    args = parser.parse_args(argv)

    with open_document("dice-roller", args) as session:
        creator, joiner, settle = session
        rolled = creator.roll(random.Random(4))
        settle()
        assert joiner.value == rolled
        again = joiner.roll(random.Random(9))
        settle()
        assert creator.value == again
        print(f"dice_roller: both clients see {creator.value}")


if __name__ == "__main__":
    main()
