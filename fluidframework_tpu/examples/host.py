"""Host — loads example apps through the code-proposal boundary.

Reference parity: packages/hosts/base-host + examples' webpack-fluid-loader
— a host owns the CodeLoader (which app packages exist), resolves document
URLs through the Loader, and hands the app its typed default object. The
document's quorum ``code`` value — not the host's command line — names the
package, so any host with the registry can open any example document.

Endpoints: in-process ordering service by default; ``--port`` targets a
running alfred front door over TCP (the tinylicious analog).
"""

from __future__ import annotations

import contextlib
import time
import uuid

from ..framework.runtime_factory import (
    ContainerRuntimeFactoryWithDefaultDataStore,
)
from ..runtime.loader import CODE_KEY, CodeLoader, Loader
from ..runtime.container import Container


def _example_factories():
    from .clicker import clicker_factory
    from .collab_text import collab_text_factory
    from .dice_roller import dice_roller_factory
    from .rich_text_editor import rich_text_editor_factory
    from .table_document import table_document_factory
    from .task_board import task_board_factory
    from .whiteboard import whiteboard_factory
    return {f.type: f for f in (clicker_factory, collab_text_factory,
                                rich_text_editor_factory,
                                task_board_factory, dice_roller_factory,
                                whiteboard_factory,
                                table_document_factory)}


class ExampleRuntimeFactory:
    """IRuntimeFactory for one example package: the channel registry plus
    the typed default-object bootstrap."""

    def __init__(self, data_object_factory) -> None:
        self.runtime_factory = ContainerRuntimeFactoryWithDefaultDataStore(
            data_object_factory)

    def instantiate(self, container: Container) -> None:
        pass  # the default registry already covers every built-in DDS

    def create_default(self, container: Container, props=None):
        return self.runtime_factory.default_factory.create(
            container.runtime,
            ContainerRuntimeFactoryWithDefaultDataStore.DEFAULT_ID,
            root=True, props=props)

    def default_object(self, container: Container):
        return self.runtime_factory.get_default_object(container)


def build_code_loader() -> CodeLoader:
    """The host's package registry (web-code-loader analog)."""
    code_loader = CodeLoader()
    for name, factory in _example_factories().items():
        code_loader.register(f"@examples/{name}",
                             ExampleRuntimeFactory(factory))
    return code_loader


def create_document(loader: Loader, package: str, url: str, props=None):
    """New document running ``package``; returns (container, data object)."""
    container = loader.create_detached({"package": package}, url)
    factory: ExampleRuntimeFactory = loader.code_loader.load(
        {"package": package})
    obj = factory.create_default(container, props)
    container.attach()
    return container, obj


def open_existing(loader: Loader, url: str):
    """Open by URL; the quorum's code value picks the app package."""
    container = loader.resolve(url)
    code = container.protocol.quorum.get(CODE_KEY)
    factory: ExampleRuntimeFactory = loader.code_loader.load(code)
    return container, factory.default_object(container)


# -- example-main plumbing -----------------------------------------------------


def parse_endpoint_args(parser) -> None:
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=None,
                        help="alfred front door port; omitted = in-process")
    parser.add_argument("--doc", default=None, help="document id")


class Session:
    """What :func:`open_document` yields: two clients on one document.

    ``created`` is False when ``--doc`` named a document that already
    existed — the session then joined it instead of clobbering it, and
    example asserts about exact fresh-document values don't hold.
    """

    def __init__(self, creator, joiner, settle, created: bool) -> None:
        self.creator = creator
        self.joiner = joiner
        self.settle = settle
        self.created = created

    def __iter__(self):
        return iter((self.creator, self.joiner, self.settle))


@contextlib.contextmanager
def open_document(example: str, args, props=None):
    """Open (creating if absent) a document for ``example``, join it with a
    second client, and yield a :class:`Session`. settle() drains until both
    replicas have seen every op. Single-threaded by construction: in
    network mode the drivers run with auto_dispatch off and settle() pumps
    inbound events on this thread — no locking needed."""
    doc_id = args.doc or f"{example}-{uuid.uuid4().hex[:8]}"
    package = f"@examples/{example}"
    containers: list[Container] = []
    services = []

    if args.port is None:
        from ..drivers.local_driver import LocalDocumentService
        from ..server.routerlicious import RouterliciousService
        service = RouterliciousService()

        def service_factory(doc):
            svc = LocalDocumentService(service, doc)
            services.append(svc)
            return svc

        def settle(timeout: float = 15.0):
            service.pump()
    else:
        from ..drivers.network_driver import NetworkDocumentService

        def service_factory(doc):
            svc = NetworkDocumentService(args.host, args.port, doc,
                                         auto_dispatch=False)
            services.append(svc)
            return svc

        def settle(timeout: float = 15.0):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                for svc in services:
                    svc.pump_events()
                pending = any(c.runtime.pending.has_pending
                              for c in containers)
                seqs = {c.last_processed_seq for c in containers}
                if not pending and len(seqs) == 1:
                    return
                time.sleep(0.02)
            raise TimeoutError("replicas failed to settle")

    loader = Loader(service_factory, build_code_loader())
    url = f"fluid://{args.host}/{doc_id}"
    # --doc may name a live document: join it, don't clobber it (a second
    # attach snapshot over replayed deltas corrupts state).
    exists = (args.doc is not None
              and service_factory(doc_id).storage.get_latest_snapshot()
              is not None)
    if exists:
        creator_container, creator = open_existing(loader, url)
        created = False
    else:
        creator_container, creator = create_document(loader, package, url,
                                                     props)
        created = True
    containers.append(creator_container)
    settle()
    joiner_container, joiner = open_existing(loader, url)
    containers.append(joiner_container)
    settle()
    try:
        yield Session(creator, joiner, settle, created)
    finally:
        for svc in services:
            close = getattr(svc, "close", None)
            if close is not None:
                close()


def main(argv: list[str] | None = None) -> None:
    """Run every example end-to-end (host smoke)."""
    from . import clicker, collab_text, task_board
    for module in (clicker, collab_text, task_board):
        module.main(argv)


if __name__ == "__main__":
    main()
