"""TableDocument — collaborative spreadsheet over SharedMatrix.

Reference parity: examples/data-objects/table-document (+ table-view):
a SharedMatrix holds the cells (row/col inserts get merge-tree OT, cell
writes are LWW), a SharedMap holds per-column headers, and "=SUM(...)"
formulas evaluate client-side over the converged grid — concurrent
structural edits (one user inserting a row while another sets cells)
resolve deterministically on every replica.

Run:  python -m fluidframework_tpu.examples.table_document
"""

from __future__ import annotations

from typing import Any

from ..dds.map import SharedMap
from ..dds.matrix import SharedMatrix
from ..framework.data_object import DataObject
from ..framework.data_object_factory import DataObjectFactory

GRID_ID = "grid"
HEADERS_ID = "headers"


class TableDocument(DataObject):
    def initializing_first_time(self, props=None) -> None:
        grid = self.runtime.create_channel(GRID_ID,
                                           SharedMatrix.channel_type)
        headers = self.runtime.create_channel(HEADERS_ID,
                                              SharedMap.channel_type)
        self.root.set(GRID_ID, grid.handle)
        self.root.set(HEADERS_ID, headers.handle)

    @property
    def grid(self) -> SharedMatrix:
        return self.root.get(GRID_ID).get()

    @property
    def headers(self) -> SharedMap:
        return self.root.get(HEADERS_ID).get()

    # -- table operations ------------------------------------------------------

    def ensure_size(self, rows: int, cols: int) -> None:
        if self.grid.row_count < rows:
            self.grid.insert_rows(self.grid.row_count,
                                  rows - self.grid.row_count)
        if self.grid.col_count < cols:
            self.grid.insert_cols(self.grid.col_count,
                                  cols - self.grid.col_count)

    def set_header(self, col: int, name: str) -> None:
        self.headers.set(f"c{col}", name)

    def set_cell(self, row: int, col: int, value: Any) -> None:
        self.grid.set_cell(row, col, value)

    def insert_row(self, pos: int) -> None:
        self.grid.insert_rows(pos, 1)

    def value_at(self, row: int, col: int) -> Any:
        """Cell value with client-side formula evaluation: a string
        "=SUM(c)" sums column c's numeric cells (table-view's eval)."""
        raw = self.grid.get_cell(row, col)
        if isinstance(raw, str) and raw.startswith("=SUM(") \
                and raw.endswith(")"):
            col_idx = int(raw[5:-1])
            total = 0
            for r in range(self.grid.row_count):
                if r == row:
                    continue
                cell = self.grid.get_cell(r, col_idx)
                if isinstance(cell, (int, float)):
                    total += cell
            return total
        return raw

    def table(self) -> list[list[Any]]:
        return [[self.value_at(r, c) for c in range(self.grid.col_count)]
                for r in range(self.grid.row_count)]


table_document_factory = DataObjectFactory("table-document", TableDocument)


def main(argv=None) -> None:
    import argparse

    from .host import open_document, parse_endpoint_args

    parser = argparse.ArgumentParser(description=__doc__)
    parse_endpoint_args(parser)
    args = parser.parse_args(argv)

    with open_document("table-document", args) as session:
        creator, joiner, settle = session
        creator.ensure_size(3, 2)
        creator.set_header(0, "qty")
        creator.set_header(1, "price")
        settle()
        # One user fills cells while the other inserts a row above them
        # — the permutation vector keeps every value on ITS row.
        creator.set_cell(0, 0, 10)
        creator.set_cell(1, 0, 32)
        joiner.insert_row(0)
        settle()
        assert creator.grid.row_count == joiner.grid.row_count == 4
        # The filled cells slid down with the inserted row.
        assert [creator.grid.get_cell(r, 0) for r in range(4)] == \
            [None, 10, 32, None]
        creator.set_cell(3, 0, "=SUM(0)")
        settle()
        assert creator.table() == joiner.table()
        assert creator.value_at(3, 0) == joiner.value_at(3, 0) == 42
        print(f"table_document: {creator.table()}")


if __name__ == "__main__":
    main()
