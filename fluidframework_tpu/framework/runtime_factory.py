"""ContainerRuntimeFactoryWithDefaultDataStore.

Reference parity: packages/framework/aqueduct/src/container-runtime-
factories/containerRuntimeFactoryWithDefaultDataStore.ts:25 — assembles a
container whose "/" resolves to a default data object, with a registry of
data-object factories for any further objects created at runtime.
"""

from __future__ import annotations

from typing import Any

from ..drivers.base import DocumentService
from ..runtime.container import Container
from .data_object_factory import DataObjectFactory
from .data_object import PureDataObject


class ContainerRuntimeFactoryWithDefaultDataStore:
    DEFAULT_ID = "default"

    def __init__(self, default_factory: DataObjectFactory,
                 registry_entries: list[DataObjectFactory] | None = None
                 ) -> None:
        self.default_factory = default_factory
        self.registry: dict[str, DataObjectFactory] = {
            f.type: f for f in (registry_entries or [])}
        self.registry.setdefault(default_factory.type, default_factory)
        self._router = None  # built lazily, reused across requests

    # -- document lifecycle ---------------------------------------------------

    def create_document(self, service: DocumentService,
                        props: Any = None) -> tuple[Container, PureDataObject]:
        """New detached document with the default object at "/default";
        caller attaches when ready (container.ts detached lifecycle)."""
        container = Container.create_detached(service)
        obj = self.default_factory.create(
            container.runtime, self.DEFAULT_ID, root=True, props=props)
        return container, obj

    def load_document(self, service: DocumentService
                      ) -> tuple[Container, PureDataObject]:
        container = Container.load(service)
        return container, self.get_default_object(container)

    # -- request routing ("/" → default object) -------------------------------

    def get_default_object(self, container: Container) -> PureDataObject:
        return self.get_object(container, self.DEFAULT_ID)

    def get_object(self, container: Container,
                   datastore_id: str) -> PureDataObject:
        """Resolve a data store id to its typed DataObject. Type→factory
        resolution lives in data_object_request_handler (one code path);
        this adds the raising contract."""
        from .request_handler import (
            RequestParser, data_object_request_handler)
        response = data_object_request_handler(self.registry)(
            RequestParser(f"/{datastore_id}"), container.runtime)
        if response is None:
            datastore = container.runtime.get_datastore(datastore_id)
            raise KeyError("no data object factory registered for "
                           f"{datastore.attributes.get('type')!r}")
        return response.value

    def create_object(self, container: Container, factory_type: str,
                      props: Any = None) -> PureDataObject:
        """Create a further (non-root) data object at runtime; store its
        handle somewhere reachable or GC will report it unreferenced."""
        return self.registry[factory_type].create(
            container.runtime, props=props)

    # -- request routing (request-handler chain) ------------------------------

    def make_router(self):
        """The assembled handler chain this factory serves: "/" rewrites to
        the default store, then "/<id>" → typed object with "/<id>" and
        "/<id>/<channel>" raw fallbacks (buildRuntimeRequestHandler
        composition). Built once per factory — the chain is immutable."""
        from .request_handler import (
            RequestParser,
            RuntimeRequestRouter,
            data_object_request_handler,
            datastore_request_handler,
        )
        typed = data_object_request_handler(self.registry)

        def root_handler(parser, runtime):
            # "/" IS "/<default>": rewrite (headers preserved) and reuse
            # the exact same handlers so there is one code path per route.
            if parser.path_parts:
                return None
            rewritten = RequestParser(f"/{self.DEFAULT_ID}", parser.headers)
            return (typed(rewritten, runtime)
                    or datastore_request_handler(rewritten, runtime))

        return RuntimeRequestRouter(
            [root_handler, typed, datastore_request_handler])

    def request(self, container: Container, url: str):
        if self._router is None:
            self._router = self.make_router()
        return self._router.request(container.runtime, url)
