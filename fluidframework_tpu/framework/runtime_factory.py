"""ContainerRuntimeFactoryWithDefaultDataStore.

Reference parity: packages/framework/aqueduct/src/container-runtime-
factories/containerRuntimeFactoryWithDefaultDataStore.ts:25 — assembles a
container whose "/" resolves to a default data object, with a registry of
data-object factories for any further objects created at runtime.
"""

from __future__ import annotations

from typing import Any

from ..drivers.base import DocumentService
from ..runtime.container import Container
from .data_object_factory import DataObjectFactory
from .data_object import PureDataObject


class ContainerRuntimeFactoryWithDefaultDataStore:
    DEFAULT_ID = "default"

    def __init__(self, default_factory: DataObjectFactory,
                 registry_entries: list[DataObjectFactory] | None = None
                 ) -> None:
        self.default_factory = default_factory
        self.registry: dict[str, DataObjectFactory] = {
            f.type: f for f in (registry_entries or [])}
        self.registry.setdefault(default_factory.type, default_factory)

    # -- document lifecycle ---------------------------------------------------

    def create_document(self, service: DocumentService,
                        props: Any = None) -> tuple[Container, PureDataObject]:
        """New detached document with the default object at "/default";
        caller attaches when ready (container.ts detached lifecycle)."""
        container = Container.create_detached(service)
        obj = self.default_factory.create(
            container.runtime, self.DEFAULT_ID, root=True, props=props)
        return container, obj

    def load_document(self, service: DocumentService
                      ) -> tuple[Container, PureDataObject]:
        container = Container.load(service)
        return container, self.get_default_object(container)

    # -- request routing ("/" → default object) -------------------------------

    def get_default_object(self, container: Container) -> PureDataObject:
        return self.get_object(container, self.DEFAULT_ID)

    def get_object(self, container: Container,
                   datastore_id: str) -> PureDataObject:
        """Resolve a data store id to its typed DataObject via the factory
        registry (request-handler equivalent)."""
        datastore = container.runtime.get_datastore(datastore_id)
        object_type = datastore.attributes.get("type")
        if object_type not in self.registry:
            raise KeyError(
                f"no data object factory registered for {object_type!r}")
        return self.registry[object_type].get(datastore)

    def create_object(self, container: Container, factory_type: str,
                      props: Any = None) -> PureDataObject:
        """Create a further (non-root) data object at runtime; store its
        handle somewhere reachable or GC will report it unreferenced."""
        return self.registry[factory_type].create(
            container.runtime, props=props)
