"""Dependency synthesizer — provider registry + scoped injection.

Reference parity: packages/framework/synthesize — ``DependencyContainer``
registers providers under capability keys (IFluidObject interface names)
and synthesizes a scope object exposing required + optional providers;
containers chain to a parent for fallback resolution. Providers may be
instances, factories (called once, cached), or already-resolved values.
"""

from __future__ import annotations

from typing import Any, Callable

_UNSET = object()


class DependencyError(KeyError):
    pass


class _Provider:
    def __init__(self, value: Any = _UNSET,
                 factory: Callable[[], Any] | None = None) -> None:
        self._value = value
        self._factory = factory

    def resolve(self) -> Any:
        if self._value is _UNSET:
            assert self._factory is not None
            self._value = self._factory()  # lazy singleton, like the ref's
        return self._value


class SynthesizedScope:
    """What synthesize() returns: providers as attributes; optional ones
    missing resolve to None (the reference's FluidObject<Optional...>)."""

    def __init__(self, resolved: dict[str, Any]) -> None:
        self.__dict__.update(resolved)

    def __getitem__(self, key: str) -> Any:
        return self.__dict__[key]


class DependencyContainer:
    def __init__(self, parent: "DependencyContainer | None" = None) -> None:
        self._parent = parent
        self._providers: dict[str, _Provider] = {}

    def register(self, key: str, value: Any = _UNSET, *,
                 factory: Callable[[], Any] | None = None) -> None:
        if (value is _UNSET) == (factory is None):
            raise ValueError("register exactly one of value= or factory=")
        self._providers[key] = _Provider(value, factory)

    def has(self, key: str) -> bool:
        if key in self._providers:
            return True
        return self._parent.has(key) if self._parent is not None else False

    def resolve(self, key: str) -> Any:
        provider = self._providers.get(key)
        if provider is not None:
            return provider.resolve()
        if self._parent is not None:
            return self._parent.resolve(key)
        raise DependencyError(f"no provider registered for {key!r}")

    def synthesize(self, required: list[str] | None = None,
                   optional: list[str] | None = None) -> SynthesizedScope:
        resolved: dict[str, Any] = {}
        for key in required or []:
            resolved[key] = self.resolve(key)  # raises when missing
        for key in optional or []:
            resolved[key] = self.resolve(key) if self.has(key) else None
        return SynthesizedScope(resolved)
