"""PureDataObject / DataObject — the app programming model.

Reference parity: packages/framework/aqueduct/src/data-objects/
pureDataObject.ts:46 and dataObject.ts:31 — a data object wraps one data
store; ``DataObject`` adds a ``root`` SharedDirectory created on first
initialization and re-bound on load.
"""

from __future__ import annotations

from typing import Any

from ..dds.directory import SharedDirectory
from ..runtime.datastore import DataStoreRuntime


class PureDataObject:
    """A typed wrapper over one data store (pureDataObject.ts:46).

    Lifecycle (mirroring the reference's initialize flow):
      - ``initializing_first_time(props)`` — runs once, on the creating
        client only, before anyone else can see the object.
      - ``initializing_from_existing()`` — runs when loading an object
        someone else created.
      - ``has_initialized()`` — runs on every client after either path.
    """

    def __init__(self, runtime: DataStoreRuntime) -> None:
        self.runtime = runtime

    # -- identity --------------------------------------------------------------

    @property
    def id(self) -> str:
        return self.runtime.id

    @property
    def handle(self):
        return self.runtime.handle

    # -- lifecycle hooks (override in subclasses) -----------------------------

    def initializing_first_time(self, props: Any = None) -> None:
        pass

    def initializing_from_existing(self) -> None:
        pass

    def has_initialized(self) -> None:
        pass


class DataObject(PureDataObject):
    """PureDataObject with a ``root`` SharedDirectory (dataObject.ts:31)."""

    ROOT_ID = "root"

    @property
    def root(self) -> SharedDirectory:
        return self.runtime.get_channel(self.ROOT_ID)
