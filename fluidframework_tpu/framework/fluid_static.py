"""FluidStatic — the simplified one-call client.

Reference parity: experimental/framework/fluid-static (+ get-container) —
``FluidContainer`` exposes named *initial objects* (DDS instances declared
up front) without the app touching data stores or channels.
"""

from __future__ import annotations

from ..dds.shared_object import SharedObject
from ..drivers.base import DocumentService
from ..runtime.container import Container

_INITIAL_DS = "initial-objects"


class FluidContainer:
    """A container exposing initial objects by name (fluid-static's
    FluidContainer.initialObjects)."""

    def __init__(self, container: Container) -> None:
        self.container = container

    @property
    def initial_objects(self) -> dict[str, SharedObject]:
        datastore = self.container.runtime.get_datastore(_INITIAL_DS)
        return {channel_id: datastore.get_channel(channel_id)
                for channel_id in datastore.channel_ids()}

    @property
    def connected(self) -> bool:
        return self.container.connected

    def disconnect(self) -> None:
        self.container.disconnect()

    def close(self) -> None:
        self.container.close()


def create_container(service: DocumentService,
                     initial_objects: dict[str, type[SharedObject]]
                     ) -> FluidContainer:
    """Create + attach a document with the given initial objects, e.g.
    ``create_container(svc, {"map": SharedMap, "text": SharedString})``."""
    container = Container.create_detached(service)
    datastore = container.runtime.create_datastore(_INITIAL_DS)
    for name, dds_cls in initial_objects.items():
        datastore.create_channel(name, dds_cls.channel_type)
    container.attach()
    return FluidContainer(container)


def get_container(service: DocumentService) -> FluidContainer:
    """Open an existing document created by :func:`create_container`."""
    return FluidContainer(Container.load(service))
