"""Undo/redo stacks driven by DDS local-edit events.

Reference parity: packages/framework/undo-redo — ``UndoRedoStackManager``
(operation grouping, undo/redo stacks) with a SharedMap handler (revert via
the valueChanged previousValue) and a SharedSegmentSequence handler (invert
insert/remove). Like the reference, reverts are submitted as ordinary local
ops — they merge like any other edit.

Limitation (v1, as in the reference's simple map handler): positions in
sequence revertibles are the positions at edit time; a revert races
concurrent remote edits like any op would.
"""

from __future__ import annotations

from typing import Callable

from ..dds.cell import SharedCell
from ..dds.counter import SharedCounter
from ..dds.map import SharedMap
from ..dds.sequence import SharedString


class Revertible:
    def __init__(self, revert: Callable[[], None],
                 discard: Callable[[], None] | None = None) -> None:
        self.revert = revert
        # Called when the revertible is dropped without reverting (redo
        # stack invalidation, stack cap) — releases tracked segments.
        self.discard = discard or (lambda: None)


class UndoRedoStackManager:
    """Groups revertibles into operations; undoing an operation records the
    inverse ops it generates as the matching redo group
    (undoRedoStackManager.ts)."""

    MAX_DEPTH = 100  # oldest operations are discarded beyond this

    def __init__(self) -> None:
        self._undo: list[list[Revertible]] = []
        self._redo: list[list[Revertible]] = []
        self._open = False  # an operation group is accumulating
        # Where newly-recorded revertibles go: the undo stack normally, the
        # in-flight inverse group while a revert is running.
        self._capture: list[Revertible] | None = None

    # -- recording -------------------------------------------------------------

    def _deliver(self, revertible: Revertible) -> None:
        if self._capture is not None:
            self._capture.append(revertible)
            return
        if not self._open or not self._undo:
            self._undo.append([])
            self._open = True
        self._undo[-1].append(revertible)
        self._drop_all(self._redo)  # a fresh edit invalidates redo
        while len(self._undo) > self.MAX_DEPTH:
            self._drop_group(self._undo.pop(0))

    @staticmethod
    def _drop_group(group: list[Revertible]) -> None:
        for revertible in group:
            revertible.discard()

    @classmethod
    def _drop_all(cls, stack: list[list[Revertible]]) -> None:
        for group in stack:
            cls._drop_group(group)
        stack.clear()

    def close_current_operation(self) -> None:
        """End the current group; the next edit starts a new undoable op."""
        self._open = False

    # -- undo/redo -------------------------------------------------------------

    @property
    def can_undo(self) -> bool:
        return bool(self._undo)

    @property
    def can_redo(self) -> bool:
        return bool(self._redo)

    def undo(self) -> None:
        self.close_current_operation()
        if self._undo:
            self._redo.append(self._revert_group(self._undo.pop()))

    def redo(self) -> None:
        if self._redo:
            self._undo.append(self._revert_group(self._redo.pop()))
            self._open = False

    def _revert_group(self, group: list[Revertible]) -> list[Revertible]:
        """Revert newest-first while capturing the inverse ops the reverts
        generate; the captured group goes on the opposite stack."""
        inverse: list[Revertible] = []
        self._capture = inverse
        try:
            for revertible in reversed(group):
                revertible.revert()
        finally:
            self._capture = None
        return inverse

    # -- DDS subscriptions -----------------------------------------------------

    def subscribe_map(self, shared_map: SharedMap) -> None:
        """Record local set/delete with the previous value
        (sharedMapUndoRedoHandler.ts)."""
        def on_value_changed(key: str, local: bool, previous,
                             existed: bool) -> None:
            if not local:
                return
            if not existed:
                self._deliver(Revertible(lambda: shared_map.delete(key)))
            else:
                self._deliver(Revertible(lambda: shared_map.set(key, previous)))

        def on_clear(local: bool, previous: dict) -> None:
            if not local:
                return

            def restore() -> None:
                for key, value in previous.items():
                    shared_map.set(key, value)
            self._deliver(Revertible(restore))
        shared_map.data.on_value_changed.append(on_value_changed)
        shared_map.data.on_clear.append(on_clear)

    def subscribe_counter(self, counter: SharedCounter) -> None:
        original = counter.increment

        def increment(delta: int = 1):
            result = original(delta)
            # Reverting calls this wrapper again, so the inverse records
            # its own inverse while a revert-capture is active.
            self._deliver(Revertible(lambda: increment(-delta)))
            return result
        counter.increment = increment  # type: ignore[method-assign]

    def subscribe_cell(self, cell: SharedCell) -> None:
        original_set, original_delete = cell.set, cell.delete

        def record_inverse(previous, was_empty: bool) -> None:
            if was_empty:
                self._deliver(Revertible(lambda: delete_()))
            else:
                self._deliver(Revertible(lambda: set_(previous)))

        def set_(value):
            previous, was_empty = cell.get(), cell.empty
            original_set(value)
            record_inverse(previous, was_empty)

        def delete_():
            previous, was_empty = cell.get(), cell.empty
            original_delete()
            if not was_empty:
                record_inverse(previous, was_empty)
        cell.set = set_        # type: ignore[method-assign]
        cell.delete = delete_  # type: ignore[method-assign]

    def subscribe_string(self, shared_string: SharedString) -> None:
        """Invert local insert/remove position-robustly: the edited segments
        ride a TrackingGroup (split tails join automatically), so the revert
        targets wherever those segments live NOW — concurrent remote edits
        shift them and the undo still hits the right content (the
        reference's merge-tree revertibles over tracking groups)."""
        from ..dds.mergetree import TrackingGroup
        engine = shared_string.engine

        def track(segments) -> TrackingGroup:
            group = TrackingGroup()
            for seg in segments:
                group.link(seg)
            return group

        def revert_insert(group: TrackingGroup) -> None:
            # Remove each tracked segment still visible, one at a time
            # (positions recomputed per call as earlier removes shift them).
            segments = list(group.segments)
            group.unlink_all()
            for seg in segments:
                if engine._vis_len(seg, engine.current_seq,
                                   engine.local_client) == 0:
                    continue  # already removed (e.g. by a remote edit)
                pos = engine.get_position(seg)
                shared_string.remove_text(pos, pos + seg.length)

        def revert_remove(group: TrackingGroup, items: list[dict],
                          fallback_start: int) -> None:
            # Reinsert at the tombstones' current position: removed segments
            # persist in the tree with zero visible length, so get_position
            # gives exactly where the gap sits after concurrent edits.
            anchor = group.segments[0] if group.segments else None
            in_tree = anchor is not None and any(
                s is anchor for s in engine.segments)
            pos = engine.get_position(anchor) if in_tree else fallback_start
            # items[i] was built from group.segments[i]; the tombstones'
            # OTHER tracking groups must adopt the restored segments (the
            # reference transfers trackingCollection on restore) so e.g.
            # undoing the original insert later also removes restored text.
            old_segments = list(group.segments)
            group.unlink_all()
            pos = min(pos, len(shared_string))
            # One-shot listener grabs each insert's new segment (the pending
            # group may already be acked re-entrantly by an in-proc server).
            captured: list = []
            hook = lambda e: captured.append(e["segments"])  # noqa: E731
            shared_string.on_local_edit.append(hook)
            try:
                for i, item in enumerate(items):
                    captured.clear()
                    props = item.get("props")
                    if "marker" in item:
                        shared_string.insert_marker(
                            pos, item["marker"]["ref_type"],
                            item["marker"]["id"], props)
                        pos += 1
                    else:
                        shared_string.insert_text(pos, item["text"], props)
                        pos += len(item["text"])
                    if i < len(old_segments) and captured:
                        new_seg = captured[-1][0]
                        for g in old_segments[i].groups:
                            if isinstance(g, TrackingGroup):
                                g.link(new_seg)
            finally:
                shared_string.on_local_edit.remove(hook)

        def revert_annotate(entries: list) -> None:
            # One tracking group per original segment keeps its prior values
            # attached across splits: every segment in a group (split tails
            # auto-join) re-annotates back to that original's prior props.
            for group, prior in entries:
                segments = list(group.segments)
                group.unlink_all()
                for seg in segments:
                    if engine._vis_len(seg, engine.current_seq,
                                       engine.local_client) == 0:
                        continue  # removed meanwhile; nothing to restore
                    pos = engine.get_position(seg)
                    shared_string.annotate_range(pos, pos + seg.length,
                                                 dict(prior))

        def on_local_edit(edit: dict) -> None:
            if edit["kind"] == "annotate":
                entries = [(track([seg]), prior)
                           for seg, prior in edit["prior"]]

                def discard_annotate() -> None:
                    for group, _prior in entries:
                        group.unlink_all()
                self._deliver(Revertible(
                    lambda: revert_annotate(entries), discard_annotate))
                return
            group = track(edit["segments"])
            if edit["kind"] == "insert":
                self._deliver(Revertible(
                    lambda: revert_insert(group), group.unlink_all))
            elif edit["kind"] == "remove":
                items, start = edit["items"], edit["start"]
                self._deliver(Revertible(
                    lambda: revert_remove(group, items, start),
                    group.unlink_all))
        shared_string.on_local_edit.append(on_local_edit)
