"""App-facing framework layer (the aqueduct equivalent).

Reference parity: packages/framework/aqueduct — ``DataObject``,
``PureDataObject``, ``DataObjectFactory``,
``ContainerRuntimeFactoryWithDefaultDataStore`` — plus the simplified
one-call client of experimental/framework/fluid-static.
"""

from .data_object import DataObject, PureDataObject
from .data_object_factory import DataObjectFactory
from .request_handler import (
    RequestParser,
    Response,
    RuntimeRequestRouter,
    data_object_request_handler,
    datastore_request_handler,
    default_route_handler,
)
from .runtime_factory import ContainerRuntimeFactoryWithDefaultDataStore
from .fluid_static import FluidContainer, create_container, get_container
from .synthesize import DependencyContainer, DependencyError

__all__ = [
    "DataObject",
    "PureDataObject",
    "DataObjectFactory",
    "ContainerRuntimeFactoryWithDefaultDataStore",
    "DependencyContainer",
    "DependencyError",
    "FluidContainer",
    "RequestParser",
    "Response",
    "RuntimeRequestRouter",
    "create_container",
    "data_object_request_handler",
    "datastore_request_handler",
    "default_route_handler",
    "get_container",
]
