"""App-facing framework layer (the aqueduct equivalent).

Reference parity: packages/framework/aqueduct — ``DataObject``,
``PureDataObject``, ``DataObjectFactory``,
``ContainerRuntimeFactoryWithDefaultDataStore`` — plus the simplified
one-call client of experimental/framework/fluid-static.
"""

from .data_object import DataObject, PureDataObject
from .data_object_factory import DataObjectFactory
from .runtime_factory import ContainerRuntimeFactoryWithDefaultDataStore
from .fluid_static import FluidContainer, create_container, get_container

__all__ = [
    "DataObject",
    "PureDataObject",
    "DataObjectFactory",
    "ContainerRuntimeFactoryWithDefaultDataStore",
    "FluidContainer",
    "create_container",
    "get_container",
]
