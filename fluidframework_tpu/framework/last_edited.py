"""LastEditedTracker — who touched the document last, durable via summary.

Reference parity: packages/framework/last-edited — watches every sequenced
runtime op and records {clientId, timestamp} into a SharedSummaryBlock
(summary-only state: updated locally on each op, persisted at summary time,
never itself an op — exactly why the reference uses a summary block here).
"""

from __future__ import annotations

from ..dds.summary_block import SharedSummaryBlock
from ..protocol.messages import MessageType, SequencedDocumentMessage
from ..runtime.container import Container

LAST_EDITED_KEY = "lastEdited"


class LastEditedTracker:
    def __init__(self, container: Container,
                 summary_block: SharedSummaryBlock) -> None:
        self._block = summary_block
        container.on_op_processed.append(self._on_op)

    def _on_op(self, message: SequencedDocumentMessage) -> None:
        if message.type != MessageType.OPERATION:
            return  # only real edits count (lastEditedTracker.ts filter)
        self._block.set(LAST_EDITED_KEY, {
            "client_id": message.client_id,
            "sequence_number": message.sequence_number,
            "timestamp": message.timestamp,
        })

    @property
    def last_edited(self) -> dict | None:
        return self._block.get(LAST_EDITED_KEY)
