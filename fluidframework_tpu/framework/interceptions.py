"""DDS interception wrappers — decorate edits without changing the DDS.

Reference parity: packages/framework/dds-interceptions — e.g.
``createSharedMapWithInterception`` (wrap set to stamp attribution props)
and the SharedString props interception. The wrapper delegates everything
else to the underlying DDS, so both views observe the same state.
"""

from __future__ import annotations

from typing import Any, Callable

from ..dds.map import SharedMap
from ..dds.sequence import SharedString


class _Intercepted:
    """Delegating proxy: attribute access falls through to the target."""

    def __init__(self, target: Any) -> None:
        object.__setattr__(self, "_target", target)

    def __getattr__(self, name: str) -> Any:
        return getattr(self._target, name)

    def __setattr__(self, name: str, value: Any) -> None:
        setattr(self._target, name, value)

    def __len__(self) -> int:
        return len(self._target)


class InterceptedSharedMap(_Intercepted):
    def __init__(self, target: SharedMap,
                 set_interceptor: Callable[[str, Any], Any]) -> None:
        super().__init__(target)
        object.__setattr__(self, "_set_interceptor", set_interceptor)

    def set(self, key: str, value: Any):
        self._target.set(key, self._set_interceptor(key, value))
        return self


class InterceptedSharedString(_Intercepted):
    def __init__(self, target: SharedString,
                 props_interceptor: Callable[[dict | None], dict | None]
                 ) -> None:
        super().__init__(target)
        object.__setattr__(self, "_props_interceptor", props_interceptor)

    def insert_text(self, pos: int, text: str,
                    props: dict | None = None) -> None:
        self._target.insert_text(pos, text, self._props_interceptor(props))

    def annotate_range(self, start: int, end: int, props: dict) -> None:
        self._target.annotate_range(start, end,
                                    self._props_interceptor(props) or {})


def create_map_with_interception(
        shared_map: SharedMap,
        set_interceptor: Callable[[str, Any], Any]) -> InterceptedSharedMap:
    """``set_interceptor(key, value) -> value`` transforms every stored
    value (e.g. wrap with attribution metadata)."""
    return InterceptedSharedMap(shared_map, set_interceptor)


def create_string_with_interception(
        shared_string: SharedString,
        props_interceptor: Callable[[dict | None], dict | None]
) -> InterceptedSharedString:
    """``props_interceptor(props) -> props`` decorates every inserted /
    annotated range (e.g. stamp the author's user id)."""
    return InterceptedSharedString(shared_string, props_interceptor)
