"""Runtime request routing — URL paths → objects inside a container.

Reference parity: packages/framework/request-handler (RuntimeRequestHandler
chain, ``buildRuntimeRequestHandler``) + the core-interfaces IResponse
shape {status, mimeType, value}. A router holds an ordered handler list;
the first handler returning a response wins; no match = 404 — exactly the
reference's composition model (e.g. defaultRouteRequestHandler +
dataStore-by-id fallback).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable


@dataclass(slots=True)
class RequestParser:
    """Split a request URL into path segments + headers (requestParser.ts)."""

    url: str
    headers: dict = field(default_factory=dict)

    @property
    def path_parts(self) -> list[str]:
        return [p for p in self.url.split("?")[0].split("/") if p]


@dataclass(slots=True)
class Response:
    status: int
    value: Any = None
    mime_type: str = "fluid/object"

    @property
    def ok(self) -> bool:
        return self.status == 200


def ok(value: Any, mime_type: str = "fluid/object") -> Response:
    return Response(200, value, mime_type)


def not_found(url: str) -> Response:
    return Response(404, f"no route for {url!r}", "text/plain")


# A handler: (RequestParser, container_runtime) -> Response | None.
RequestHandler = Callable[[RequestParser, Any], "Response | None"]


class RuntimeRequestRouter:
    """Ordered handler chain (buildRuntimeRequestHandler)."""

    def __init__(self, handlers: list[RequestHandler] | None = None) -> None:
        self._handlers = list(handlers or [])

    def add(self, handler: RequestHandler) -> "RuntimeRequestRouter":
        self._handlers.append(handler)
        return self

    def request(self, runtime, url: str,
                headers: dict | None = None) -> Response:
        parser = RequestParser(url, headers or {})
        for handler in self._handlers:
            response = handler(parser, runtime)
            if response is not None:
                return response
        return not_found(url)


# -- built-in handlers ---------------------------------------------------------


def default_route_handler(default_id: str) -> RequestHandler:
    """"/" → the default data store (defaultRouteRequestHandler)."""

    def handler(parser: RequestParser, runtime) -> Response | None:
        if parser.path_parts:
            return None
        try:
            return ok(runtime.get_datastore(default_id))
        except KeyError:
            return None
    return handler


def datastore_request_handler(parser: RequestParser, runtime
                              ) -> Response | None:
    """"/<datastore>[/<channel>]" → data store or channel inside it."""
    parts = parser.path_parts
    if not parts:
        return None
    try:
        datastore = runtime.get_datastore(parts[0])
    except KeyError:
        return None
    if len(parts) == 1:
        return ok(datastore)
    if len(parts) == 2:
        try:
            return ok(datastore.get_channel(parts[1]))
        except KeyError:
            return None
    return None


def data_object_request_handler(registry: dict) -> RequestHandler:
    """"/<datastore>" → the TYPED data object via its factory registry
    (the aqueduct get_object path as a chain handler)."""

    def handler(parser: RequestParser, runtime) -> Response | None:
        parts = parser.path_parts
        if len(parts) != 1:
            return None
        try:
            datastore = runtime.get_datastore(parts[0])
        except KeyError:
            return None
        factory = registry.get(datastore.attributes.get("type"))
        if factory is None:
            return None
        return ok(factory.get(datastore))
    return handler
