"""DataObjectFactory — creates/loads DataObjects over data stores.

Reference parity: packages/framework/aqueduct/src/data-object-factories/
dataObjectFactory.ts:32 — binds an object type string to a DataObject class,
creates the backing data store (plus the root directory for ``DataObject``
subclasses), and runs the initialize lifecycle.
"""

from __future__ import annotations

import uuid
from typing import Any

from ..dds.directory import SharedDirectory
from ..runtime.container_runtime import ContainerRuntime
from ..runtime.datastore import DataStoreRuntime
from .data_object import DataObject, PureDataObject


class DataObjectFactory:
    def __init__(self, object_type: str,
                 data_object_cls: type[PureDataObject] = DataObject) -> None:
        self.type = object_type
        self.data_object_cls = data_object_cls

    # -- create ---------------------------------------------------------------

    def create(self, container_runtime: ContainerRuntime,
               datastore_id: str | None = None, root: bool = False,
               props: Any = None) -> PureDataObject:
        """Create a new instance: data store + root channel + first-time
        init (dataObjectFactory.ts createInstance flow)."""
        if datastore_id is None:
            # Globally unique (uuid, as in the reference): two clients
            # auto-creating objects must never collide on a store id —
            # process_attach would silently merge them.
            datastore_id = f"{self.type}-{uuid.uuid4().hex}"
        datastore = container_runtime.create_datastore(
            datastore_id, root=root, attributes={"type": self.type})
        obj = self.data_object_cls(datastore)
        if issubclass(self.data_object_cls, DataObject):
            datastore.create_channel(DataObject.ROOT_ID,
                                     SharedDirectory.channel_type)
        obj.initializing_first_time(props)
        obj.has_initialized()
        datastore._data_object = obj  # later get()s return the creator's
        return obj

    # -- load -----------------------------------------------------------------

    def get(self, datastore: DataStoreRuntime) -> PureDataObject:
        """Wrap an existing (loaded) data store of this factory's type.
        Cached per data store: repeated gets (every routed request) must
        not re-run the initialize lifecycle — hooks that subscribe
        listeners would stack one copy per call."""
        assert datastore.attributes.get("type") == self.type, (
            f"data store {datastore.id!r} is "
            f"{datastore.attributes.get('type')!r}, not {self.type!r}")
        cached = getattr(datastore, "_data_object", None)
        if isinstance(cached, self.data_object_cls):
            return cached
        obj = self.data_object_cls(datastore)
        obj.initializing_from_existing()
        obj.has_initialized()
        datastore._data_object = obj
        return obj
