"""Layered configuration: defaults < file < environment < overrides.

Reference parity: the server's nconf stack (services-utils; per-service
``config.json`` — routerlicious/config/config.json:1-80) and the client's
``ILoaderOptions``/``IContainerRuntimeOptions`` plumbing
(containerRuntime.ts:1407). One Config object serves both sides here.

Lookup keys are colon-separated paths (nconf style): ``cfg.get("bus:partitions")``.
Environment variables override with prefix ``FF_TPU_`` and ``__`` as the
path separator: ``FF_TPU_BUS__PARTITIONS=8``. Values from env parse as
JSON when possible (so numbers/bools/objects round-trip), else stay strings.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any

ENV_PREFIX = "FF_TPU_"
_MISSING = object()


def _deep_merge(base: dict, overlay: dict) -> dict:
    out = dict(base)
    for key, value in overlay.items():
        if isinstance(value, dict) and isinstance(out.get(key), dict):
            out[key] = _deep_merge(out[key], value)
        else:
            out[key] = value
    return out


class Config:
    def __init__(self, defaults: dict[str, Any] | None = None,
                 file: str | os.PathLike | None = None,
                 env: dict[str, str] | None = None,
                 overrides: dict[str, Any] | None = None) -> None:
        layers: list[dict[str, Any]] = [dict(defaults or {})]
        if file is not None and Path(file).exists():
            layers.append(json.loads(Path(file).read_text()))
        layers.append(self._from_env(env if env is not None
                                     else dict(os.environ)))
        layers.append(dict(overrides or {}))
        merged: dict[str, Any] = {}
        for layer in layers:
            merged = _deep_merge(merged, layer)
        self._data = merged

    @staticmethod
    def _from_env(env: dict[str, str]) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for key, raw in env.items():
            if not key.startswith(ENV_PREFIX):
                continue
            path = key[len(ENV_PREFIX):].lower().split("__")
            try:
                value: Any = json.loads(raw)
            except ValueError:
                value = raw
            node = out
            for part in path[:-1]:
                node = node.setdefault(part, {})
            node[path[-1]] = value
        return out

    def get(self, path: str, default: Any = None) -> Any:
        node: Any = self._data
        for part in path.split(":"):
            if not isinstance(node, dict):
                return default
            node = node.get(part, _MISSING)
            if node is _MISSING:
                return default
        return node

    def require(self, path: str) -> Any:
        value = self.get(path, _MISSING)
        if value is _MISSING:
            raise KeyError(f"missing required config {path!r}")
        return value

    def section(self, path: str) -> "Config":
        sub = self.get(path, {})
        cfg = Config.__new__(Config)
        cfg._data = sub if isinstance(sub, dict) else {}
        return cfg

    def as_dict(self) -> dict[str, Any]:
        return json.loads(json.dumps(self._data))  # deep copy


DEFAULTS: dict[str, Any] = {
    "bus": {"partitions": 4},
    "alfred": {"max_message_size": 16 * 1024,  # config.json:38
               "throttle": {"rate_per_interval": 1_000_000,
                            "interval_ms": 1000}},
    "deli": {"client_timeout_ms": 300_000},
    "merge_host": {"tick_ops": 64, "seg_slots": 64, "map_slots": 32},
    "summary": {"max_ops": 100, "idle_time_ms": 5000,
                "max_time_ms": 60_000},
    "runtime": {"max_op_bytes": 16 * 1024},  # chunk above this
}


def default_config(overrides: dict[str, Any] | None = None,
                   file: str | os.PathLike | None = None) -> Config:
    return Config(defaults=DEFAULTS, file=file, overrides=overrides)
