"""Structured telemetry: logger hierarchy, perf spans, wall-clock traces.

Reference parity: packages/utils/telemetry-utils/src/logger.ts —
``TelemetryLogger`` (:103, namespace prefixing + property stamping),
``ChildLogger`` (:238), ``MultiSinkLogger`` (:314), ``PerformanceEvent``
(:356, start/end/cancel spans with duration); common-utils/src/trace.ts
(``Trace.trace()`` monotonic split timer); debugLogger.ts (console sink).

Events are plain dicts: {"category", "eventName", ...props}. Categories
follow the reference: "generic" | "performance" | "error".
"""

from __future__ import annotations

import collections
import json
import logging
import threading
import time
from typing import Any, Callable


class TelemetryLogger:
    """Base logger: namespace prefixing + fixed properties.

    Subclasses implement :meth:`send`. ``namespace`` prefixes every event
    name (``fluid:telemetry`` analog); ``properties`` are stamped onto every
    event (reference ``ITelemetryLoggerPropertyBags``).
    """

    EVENT_NAME_SEPARATOR = ":"

    def __init__(self, namespace: str | None = None,
                 properties: dict[str, Any] | None = None) -> None:
        self.namespace = namespace
        self.properties = dict(properties or {})

    def send(self, event: dict[str, Any]) -> None:
        raise NotImplementedError

    def _prepare(self, event: dict[str, Any]) -> dict[str, Any]:
        out = dict(self.properties)
        out.update(event)
        if self.namespace:
            out["eventName"] = (self.namespace + self.EVENT_NAME_SEPARATOR
                                + out.get("eventName", ""))
        out.setdefault("category", "generic")
        return out

    # -- convenience levels (logger.ts sendTelemetryEvent/ErrorEvent) ---------

    def send_event(self, event_name: str, **props: Any) -> None:
        self.send(self._prepare({"eventName": event_name, **props}))

    def send_error(self, event_name: str, error: BaseException | str | None
                   = None, **props: Any) -> None:
        if error is not None:
            props["error"] = repr(error) if isinstance(error, BaseException) \
                else error
        self.send(self._prepare({"eventName": event_name,
                                 "category": "error", **props}))

    def send_performance(self, event_name: str, duration_ms: float,
                         **props: Any) -> None:
        self.send(self._prepare({"eventName": event_name,
                                 "category": "performance",
                                 "duration": duration_ms, **props}))


class NullLogger(TelemetryLogger):
    """Drops everything — the default sink when the host injects none."""

    def send(self, event: dict[str, Any]) -> None:
        pass


class CollectingLogger(TelemetryLogger):
    """Buffers events in memory — the test sink."""

    def __init__(self, namespace: str | None = None,
                 properties: dict[str, Any] | None = None) -> None:
        super().__init__(namespace, properties)
        self.events: list[dict[str, Any]] = []

    def send(self, event: dict[str, Any]) -> None:
        self.events.append(event)

    def matching(self, event_name_suffix: str) -> list[dict[str, Any]]:
        return [e for e in self.events
                if e.get("eventName", "").endswith(event_name_suffix)]


class DebugLogger(TelemetryLogger):
    """Routes events to stdlib logging as single-line JSON
    (debugLogger.ts; server side mirrors winston's JSON lines)."""

    def __init__(self, namespace: str | None = None,
                 properties: dict[str, Any] | None = None,
                 logger: logging.Logger | None = None) -> None:
        super().__init__(namespace, properties)
        self._logger = logger or logging.getLogger("fluid.telemetry")

    def send(self, event: dict[str, Any]) -> None:
        level = logging.ERROR if event.get("category") == "error" \
            else logging.INFO
        self._logger.log(level, json.dumps(event, default=str))


class ChildLogger(TelemetryLogger):
    """Namespace/property extension over a parent sink (logger.ts:238)."""

    def __init__(self, parent: TelemetryLogger, namespace: str | None = None,
                 properties: dict[str, Any] | None = None) -> None:
        combined = (parent.namespace + TelemetryLogger.EVENT_NAME_SEPARATOR
                    + namespace) if parent.namespace and namespace \
            else (namespace or parent.namespace)
        props = dict(parent.properties)
        props.update(properties or {})
        super().__init__(combined, props)
        self._parent = parent

    @staticmethod
    def create(parent: TelemetryLogger | None, namespace: str | None = None,
               properties: dict[str, Any] | None = None) -> "ChildLogger":
        return ChildLogger(parent or NullLogger(), namespace, properties)

    def send(self, event: dict[str, Any]) -> None:
        # Namespace/props were already applied by _prepare on this logger;
        # forward raw to the root sink.
        self._parent.send(event)


class MultiSinkLogger(TelemetryLogger):
    """Broadcasts every event to several sinks (logger.ts:314)."""

    def __init__(self, sinks: list[TelemetryLogger] | None = None) -> None:
        super().__init__()
        self.sinks = list(sinks or [])

    def add_sink(self, sink: TelemetryLogger) -> None:
        self.sinks.append(sink)

    def send(self, event: dict[str, Any]) -> None:
        for sink in self.sinks:
            sink.send(event)


class PerfTrace:
    """Monotonic split timer (common-utils trace.ts ``Trace``): ``trace()``
    returns (total_ms, since_last_ms) and resets the split point."""

    def __init__(self) -> None:
        self._start = time.perf_counter()
        self._last = self._start

    def trace(self) -> tuple[float, float]:
        now = time.perf_counter()
        total = (now - self._start) * 1000.0
        split = (now - self._last) * 1000.0
        self._last = now
        return total, split


class PerformanceEvent:
    """Telemetry span: emits <name>_start / <name>_end / <name>_cancel with
    duration (logger.ts:356). Usable as a context manager — exceptions emit
    cancel and re-raise, mirroring ``PerformanceEvent.timedExec``."""

    def __init__(self, logger: TelemetryLogger, event_name: str,
                 emit_start: bool = False, **props: Any) -> None:
        self._logger = logger
        self._name = event_name
        self._props = props
        self._trace = PerfTrace()
        self._done = False
        if emit_start:
            logger.send_event(f"{event_name}_start", **props)

    def report_progress(self, event_name_suffix: str, **props: Any) -> None:
        total, split = self._trace.trace()
        self._logger.send_performance(
            f"{self._name}_{event_name_suffix}", split,
            **{**self._props, **props})

    def end(self, **props: Any) -> None:
        if self._done:
            return
        self._done = True
        total, _ = self._trace.trace()
        self._logger.send_performance(f"{self._name}_end", total,
                                      **{**self._props, **props})

    def cancel(self, error: BaseException | None = None, **props: Any) -> None:
        if self._done:
            return
        self._done = True
        total, _ = self._trace.trace()
        if error is not None:
            props["error"] = repr(error)
        self._logger.send_performance(f"{self._name}_cancel", total,
                                      **{**self._props, **props})

    def __enter__(self) -> "PerformanceEvent":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.cancel(exc)
        else:
            self.end()


class TraceSpans:
    """Per-op distributed-trace joiner (connectionTelemetry.ts op
    round-trip spans, generalized to the storm path): sampled frames
    carry a trace id; each hop that touches the frame calls
    :meth:`mark` with a shared monotonic-ns clock, and :meth:`finish`
    joins the marks into ONE span record — absolute ``hops`` (ns) plus
    consecutive ``deltas_ms`` — emitted through the telemetry logger
    (category "performance") and kept in a bounded ring for in-process
    consumers (bench columns, tests).

    Marks arrive from several threads (bridge pump, serving thread, WAL
    drain); a single lock serializes the tiny dict ops. Unfinished
    traces are evicted oldest-first past ``max_pending`` so a client
    that dies mid-flight can never leak marks without bound.
    """

    def __init__(self, logger: TelemetryLogger | None = None,
                 event_name: str = "OpTraceSpan",
                 capacity: int = 4096, max_pending: int = 4096) -> None:
        self._logger = logger or NullLogger()
        self._event_name = event_name
        self._marks: collections.OrderedDict = collections.OrderedDict()
        self._max_pending = max(1, max_pending)
        self.spans: collections.deque = collections.deque(
            maxlen=max(1, capacity))
        self._lock = threading.Lock()

    @staticmethod
    def now_ns() -> int:
        return time.monotonic_ns()

    def mark(self, trace_id: Any, hop: str, t_ns: int | None = None) -> None:
        t = self.now_ns() if t_ns is None else int(t_ns)
        with self._lock:
            marks = self._marks.get(trace_id)
            if marks is None:
                while len(self._marks) >= self._max_pending:
                    self._marks.popitem(last=False)
                marks = self._marks[trace_id] = []
            marks.append((hop, t))

    def hops(self, trace_id: Any) -> dict:
        """Current absolute marks of an UNFINISHED trace (hop → ns) —
        what the server stamps onto a traced ack so the client can join
        its own send/rx clocks in (same-host monotonic domain)."""
        with self._lock:
            return dict(self._marks.get(trace_id, ()))

    def finish(self, trace_id: Any, **props: Any) -> dict | None:
        """Join and emit one span; None (and no event) for an id that
        never marked — double-finish is likewise a no-op."""
        with self._lock:
            marks = self._marks.pop(trace_id, None)
        if not marks:
            return None
        t0 = marks[0][1]
        deltas = {f"{a}_to_{b}": round((tb - ta) / 1e6, 4)
                  for (a, ta), (b, tb) in zip(marks, marks[1:])}
        span = {"trace_id": trace_id, "hops": dict(marks),
                "deltas_ms": deltas,
                "total_ms": round((marks[-1][1] - t0) / 1e6, 4), **props}
        self.spans.append(span)
        self._logger.send_performance(self._event_name, span["total_ms"],
                                      trace_id=trace_id, **deltas)
        return span

    def hop_quantiles(self, qs=(0.5, 0.99)) -> dict:
        """Per-hop-delta quantiles over the finished-span ring — the
        sampled decomposition of end-to-end latency the round's bench
        rows record: {delta_name: {"p50_ms", "p99_ms", "count"}}."""
        by_hop: dict[str, list[float]] = {}
        for span in list(self.spans):
            for name, ms in span["deltas_ms"].items():
                by_hop.setdefault(name, []).append(ms)
        from .metrics import percentile
        out: dict = {}
        for name, vals in by_hop.items():
            vals.sort()
            row = {"count": len(vals)}
            for q in qs:
                row[f"p{int(q * 100)}_ms"] = round(percentile(vals, q), 4)
            out[name] = row
        return out


def timed(logger: TelemetryLogger, event_name: str,
          **props: Any) -> Callable:
    """Decorator form of PerformanceEvent.timedExec."""

    def wrap(fn: Callable) -> Callable:
        def inner(*args: Any, **kwargs: Any) -> Any:
            with PerformanceEvent(logger, event_name, **props):
                return fn(*args, **kwargs)
        inner.__name__ = fn.__name__
        return inner

    return wrap
