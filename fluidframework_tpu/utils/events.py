"""Event/async primitives shared across the stack.

Reference parity: common/lib/common-utils — ``TypedEventEmitter``
(typedEventEmitter.ts), ``Deferred``/``LazyPromise`` (promises.ts),
``BatchManager`` (batchManager.ts), ``Heap`` (heap.ts). Python needs no
promise machinery, so Deferred collapses to a set-once result latch.
"""

from __future__ import annotations

import heapq
from typing import Any, Callable, Generic, TypeVar

T = TypeVar("T")


class TypedEventEmitter:
    """Minimal synchronous emitter: on/once/off/emit by event name.

    Listener errors propagate to the emitter (the reference crashes the
    container on listener throw — error containment is the caller's job).
    """

    def __init__(self) -> None:
        self._listeners: dict[str, list[Callable[..., None]]] = {}
        self._once: dict[str, set[Callable[..., None]]] = {}

    def on(self, event: str, listener: Callable[..., None]) -> Callable[[], None]:
        self._listeners.setdefault(event, []).append(listener)
        return lambda: self.off(event, listener)

    def once(self, event: str, listener: Callable[..., None]) -> None:
        self.on(event, listener)
        self._once.setdefault(event, set()).add(listener)

    def off(self, event: str, listener: Callable[..., None]) -> None:
        listeners = self._listeners.get(event, [])
        if listener in listeners:
            listeners.remove(listener)
        self._once.get(event, set()).discard(listener)

    def emit(self, event: str, *args: Any, **kwargs: Any) -> int:
        listeners = list(self._listeners.get(event, []))
        for listener in listeners:
            if listener in self._once.get(event, set()):
                self.off(event, listener)
            listener(*args, **kwargs)
        return len(listeners)

    def listener_count(self, event: str) -> int:
        return len(self._listeners.get(event, []))


class Deferred(Generic[T]):
    """Set-once result latch (common-utils promises.ts ``Deferred``)."""

    _UNSET = object()

    def __init__(self) -> None:
        self._value: Any = Deferred._UNSET
        self._error: BaseException | None = None
        self._callbacks: list[tuple[Callable[[T], None],
                                    Callable[[BaseException], None] | None]] \
            = []

    @property
    def is_completed(self) -> bool:
        return self._value is not Deferred._UNSET or self._error is not None

    def resolve(self, value: T) -> None:
        if self.is_completed:
            return
        self._value = value
        for cb, _ in self._callbacks:
            cb(value)
        self._callbacks.clear()

    def reject(self, error: BaseException) -> None:
        if self.is_completed:
            return
        self._error = error
        for _, on_error in self._callbacks:
            if on_error is not None:
                on_error(error)
        self._callbacks.clear()

    def then(self, callback: Callable[[T], None],
             on_error: Callable[[BaseException], None] | None = None) -> None:
        if self._value is not Deferred._UNSET:
            callback(self._value)
        elif self._error is not None:
            if on_error is not None:
                on_error(self._error)
        else:
            self._callbacks.append((callback, on_error))

    @property
    def value(self) -> T:
        if self._error is not None:
            raise self._error
        if self._value is Deferred._UNSET:
            raise RuntimeError("Deferred not resolved")
        return self._value


class BatchManager(Generic[T]):
    """Accumulate items per key and flush as batches
    (common-utils batchManager.ts; used by the reference's delta connection
    to coalesce outbound ops into one socket emit).
    """

    def __init__(self, process: Callable[[str, list[T]], None],
                 max_batch_size: int = 100) -> None:
        self._process = process
        self._max = max_batch_size
        self._pending: dict[str, list[T]] = {}

    def add(self, key: str, item: T) -> None:
        batch = self._pending.setdefault(key, [])
        batch.append(item)
        if len(batch) >= self._max:
            self.drain(key)

    def drain(self, key: str | None = None) -> None:
        keys = [key] if key is not None else list(self._pending)
        for k in keys:
            batch = self._pending.pop(k, [])
            if batch:
                self._process(k, batch)


class Heap(Generic[T]):
    """Min-heap with explicit comparison key (common-utils heap.ts).

    The reference uses it for MSN tracking and timer wheels; here it backs
    the delta scheduler and summarizer heuristics.
    """

    def __init__(self, key: Callable[[T], Any] = lambda x: x) -> None:
        self._key = key
        self._items: list[tuple[Any, int, T]] = []
        self._counter = 0  # tie-break, keeps heapq away from T comparisons

    def __len__(self) -> int:
        return len(self._items)

    def push(self, item: T) -> None:
        self._counter += 1
        heapq.heappush(self._items, (self._key(item), self._counter, item))

    def peek(self) -> T:
        return self._items[0][2]

    def pop(self) -> T:
        return heapq.heappop(self._items)[2]
