"""Persistent XLA compilation cache for serving hosts and test farms.

The serving host's device programs recompile whenever a pool migrates to
a new shape bucket (segment slots, op-batch k, prop planes, overlap
words all double on demand). Within one process the in-memory jit cache
dedups identical shapes; across restarts — a serving host rolling, a
farm re-running, bench.py re-invoked — every bucket shape would pay its
full XLA compile again (~1-3s each on CPU, 20-40s cold on TPU). The
reference ships its lambdas warm for the same reason (a routerlicious
pod restart does not re-JIT V8 code from scratch); here the equivalent
is JAX's persistent compilation cache keyed by HLO fingerprint.

Call :func:`enable` before first device use. Opt out with
``FFTPU_COMPILE_CACHE=0``; override the location with
``FFTPU_COMPILE_CACHE_DIR``.
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "fluidframework_tpu", "xla")

_enabled = False
_active_dir: str | None = None


def enable(cache_dir: str | None = None) -> str | None:
    """Idempotently turn on the persistent compilation cache.

    Returns the cache directory, or None when disabled by env."""
    global _enabled, _active_dir
    if os.environ.get("FFTPU_COMPILE_CACHE", "1") == "0":
        return None
    if _enabled:
        # Already configured: report the directory actually in effect —
        # a different requested dir is NOT adopted mid-process.
        return _active_dir
    path = (cache_dir or os.environ.get("FFTPU_COMPILE_CACHE_DIR")
            or _DEFAULT_DIR)
    os.makedirs(path, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    # Serving-host programs include many sub-second helpers (row writes,
    # margin reads) that still dominate a farm's wall clock in aggregate;
    # cache everything non-trivial rather than only the big kernels.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _enabled = True
    _active_dir = path
    return path
