"""Persistent XLA compilation cache for serving hosts and test farms.

The serving host's device programs recompile whenever a pool migrates to
a new shape bucket (segment slots, op-batch k, prop planes, overlap
words all double on demand). Within one process the in-memory jit cache
dedups identical shapes; across restarts — a serving host rolling, a
farm re-running, bench.py re-invoked — every bucket shape would pay its
full XLA compile again (~1-3s each on CPU, 20-40s cold on TPU). The
reference ships its lambdas warm for the same reason (a routerlicious
pod restart does not re-JIT V8 code from scratch); here the equivalent
is JAX's persistent compilation cache keyed by HLO fingerprint.

Call :func:`enable` before first device use. Opt out with
``FFTPU_COMPILE_CACHE=0``; override the location with
``FFTPU_COMPILE_CACHE_DIR``.
"""

from __future__ import annotations

import contextlib
import functools
import os

_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "fluidframework_tpu", "xla")

_enabled = False
_active_dir: str | None = None


def enable(cache_dir: str | None = None) -> str | None:
    """Idempotently turn on the persistent compilation cache.

    Returns the cache directory, or None when disabled by env."""
    global _enabled, _active_dir
    if os.environ.get("FFTPU_COMPILE_CACHE", "1") == "0":
        return None
    if _enabled:
        # Already configured: report the directory actually in effect —
        # a different requested dir is NOT adopted mid-process.
        return _active_dir
    path = (cache_dir or os.environ.get("FFTPU_COMPILE_CACHE_DIR")
            or _DEFAULT_DIR)
    os.makedirs(path, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    # Serving-host programs include many sub-second helpers (row writes,
    # margin reads) that still dominate a farm's wall clock in aggregate;
    # cache everything non-trivial rather than only the big kernels.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _enabled = True
    _active_dir = path
    return path


@contextlib.contextmanager
def bypass():
    """Disable the persistent cache for the duration of the block.

    Needed for DONATED serving ticks: jaxlib 0.4.37 mishandles buffer
    donation on executables DESERIALIZED from the persistent cache —
    the second execution of such an executable double-frees its donated
    inputs (glibc "corrupted double-linked list" under the mixed
    all-DDS tick; reproduced at /tmp with a two-process warm run of any
    multi-tick mixed assembly, cold compiles unaffected). The donated
    hot ticks therefore always compile in-process: they trade warm-start
    seconds for correctness and keep in-place HBM donation."""
    # The config dir is snapshotted into a singleton at first use, so a
    # config context is a no-op once any jit compiled; the per-compile
    # gate jax actually consults is the cached ``_cache_used`` verdict
    # (compilation_cache.is_cache_used) — flip that for the block.
    global _enabled, _active_dir
    try:
        from jax._src import compilation_cache as cc
        with cc._cache_initialized_mutex:
            prev = (cc._cache_checked, cc._cache_used)
            cc._cache_checked, cc._cache_used = True, False
    except Exception:
        # jax internals moved: fail CLOSED. A silently inert guard would
        # reintroduce the double-free on the next warm start, so turn
        # the persistent cache off for the whole process (public config
        # — effective as long as no jit compiled yet) and say so.
        import warnings

        import jax

        warnings.warn(
            "compile_cache.bypass: jax internals changed; disabling the "
            "persistent compilation cache process-wide instead of "
            "per-call (re-audit the donated-executable double-free "
            "against this jax version)", RuntimeWarning, stacklevel=3)
        jax.config.update("jax_compilation_cache_dir", None)
        _enabled = False
        _active_dir = None
        yield
        return
    try:
        yield
    finally:
        with cc._cache_initialized_mutex:
            cc._cache_checked, cc._cache_used = prev


def uncached(jitted):
    """Wrap a donated jitted serving tick so its compile/lookup NEVER
    touches the persistent cache (see :func:`bypass`). The traced
    function stays reachable via ``__wrapped__`` (bench re-jits it
    without donation, which the cache handles fine)."""
    @functools.wraps(jitted)
    def call(*args, **kwargs):
        with bypass():
            return jitted(*args, **kwargs)
    call.__wrapped__ = getattr(jitted, "__wrapped__", jitted)
    return call
