"""Seeded fault injection — named crashpoints on the durability paths.

The chaos harness (tools/chaos.py) proves the crash-consistency story by
actually killing the serving process at the points where a crash is
dangerous and diffing the recovered state against an uninterrupted twin.
The serving code declares those points by calling :func:`crashpoint`
with a stable name; a *plan* (installed from the environment or
programmatically) hard-kills the process — ``os._exit``, no atexit, no
buffer flushing, no destructors — at the N-th hit of one named point.

Registered points (grep for ``crashpoint(`` to audit):

==========================  ==================================================
``wal.pre_fsync``           group-commit writer: records appended, NOT yet
                            fsynced (the torn-batch window)
``wal.post_fsync``          records durable, completion callbacks / acks NOT
                            yet fired (durable-but-unacknowledged window)
``storm.mid_tick``          device state mutated by the fused tick, durable
                            record NOT yet enqueued (volatile-state window)
``storm.pre_ack``           durable record fsynced, ack NOT yet pushed
``storm.overlap_dispatch``  pipelined tick N+1 dispatched while tick N's
                            group commit may still be in flight (the
                            mid-overlap window: N replays byte-identically,
                            N+1 returns only via client resend)
``storm.readback_pre_wal``  tick results read back, durable record NOT yet
                            handed to the WAL writer (readback-before-fsync:
                            the whole tick is volatile, nothing acked)
``storm.overlap_fsynced``   tick N durable and about to ack while tick N+1
                            is still in flight (fsync-complete-before-
                            readback: N+1 must never be acked early)
``pool.mid_rebalance``      block merge pool mid-rebalance (layout moving)
``pool.mid_retune``         block geometry retune mid-move (whole-pool
                            re-block; the replayed retune must re-decide
                            the same geometry)
``snapshot.mid_upload``     snapshot chunks partially written
``snapshot.pre_publish``    snapshot uploaded, head ref NOT yet flipped
``residency.mid_hydrate``   cold-doc hydration mid-restore (sequencer row
                            installed, map row NOT yet) — volatile only
``residency.mid_evict``     cold snapshot uploaded, head ref NOT yet
                            flipped, device rows still live
``residency.post_evict``    cold head flipped, device rows NOT yet
                            released (doc durable both ways)
==========================  ==================================================

A plan is inert until :func:`arm` — the harness arms only after its
setup phase (joins, genesis checkpoint) so kills always land inside the
serving window under test. With no plan installed, :func:`crashpoint`
is one attribute load and a ``None`` check.

Environment protocol (used by the chaos child process)::

    FFTPU_CRASHPOINT="wal.pre_fsync:3"   # kill at the 3rd hit

``install_from_env()`` runs at import; the child calls ``arm()`` itself.

Besides kill plans there are *failure plans* — named :func:`failpoint`
hooks that RAISE an injected exception for the next N hits instead of
killing the process (the overload/robustness fault classes: a failing
fsync is survivable-by-design, a kill is not). Registered failpoints:

==========================  ==================================================
``wal.fsync``               group-commit writer, just before the batch fsync
                            (an injected OSError here drives the WAL circuit
                            breaker into its degraded/half-open cycle)
==========================  ==================================================

Environment protocol: ``FFTPU_FAILPOINT="wal.fsync:3"`` fails the next
3 hits, then heals. Failure plans share the :func:`arm` gate with kill
plans.

The third plan family is *link faults* — named network pathologies the
:class:`~..server.transport.FaultyTransport` wrapper injects per
replication edge (see ``server/transport.py``; the chaos ``--netsplit``
scenarios install them mid-run):

==================  ==========================================================
``drop`` (p)        per-call frame loss, nothing delivered
``delay`` (s, p)    added latency before delivery
``slow`` (s)        every call slowed (a saturated link)
``dup`` (p)         delivered twice — the idempotent-redelivery path
``reorder`` (p)     held past the next frame — a genuine out-of-order arrival
``partition``       full partition: every call fails, nothing delivered
``partition_send``  one-way: requests lost before the follower sees them
``partition_recv``  one-way: delivered, but the response is lost (the
                    leader retries — duplicate delivery for real)
==================  ==========================================================

Environment protocol (parsed by :func:`link_fault_plan_from_env`)::

    FFTPU_LINKFAULTS="f0:drop@p=0.2;f0:delay@s=0.01,p=0.5;f1:partition"
"""

from __future__ import annotations

import os
import sys

#: Exit status of a planned kill — distinguishes an injected crash from a
#: real failure in the parent harness (128 + SIGKILL, the conventional
#: "killed" status).
KILL_EXIT_CODE = 137

_plan: tuple[str, int] | None = None  # (point name, kill at N-th hit)
_armed = False
_hits = 0
#: Per-point fire counts while a plan is installed (tests introspect
#: these; the no-plan hot path never touches the dict).
fired: dict[str, int] = {}
#: Failure plans: point name -> remaining armed-hit count. Emptiness is
#: the hot-path gate (one dict truthiness check when nothing installed).
_fail_plans: dict[str, int] = {}


class InjectedFault(OSError):
    """The exception a :func:`failpoint` raises — an OSError subclass so
    injected fsync/IO failures travel the same except paths real ones do,
    while staying distinguishable in assertions."""


def install(point: str, hits: int = 1) -> None:
    """Install a kill plan: die at the ``hits``-th hit of ``point``."""
    global _plan, _hits
    if hits < 1:
        raise ValueError(f"hits must be >= 1, got {hits}")
    _plan = (point, hits)
    _hits = 0
    fired.clear()


def install_failure(point: str, times: int = 1) -> None:
    """Install a failure plan: the next ``times`` armed hits of ``point``
    raise :class:`InjectedFault`, then the point heals."""
    if times < 1:
        raise ValueError(f"times must be >= 1, got {times}")
    _fail_plans[point] = times


def install_from_env() -> None:
    spec = os.environ.get("FFTPU_CRASHPOINT")
    if spec:
        point, _, hits = spec.partition(":")
        install(point, int(hits) if hits else 1)
    spec = os.environ.get("FFTPU_FAILPOINT")
    if spec:
        point, _, times = spec.partition(":")
        install_failure(point, int(times) if times else 1)


def arm() -> None:
    global _armed
    _armed = True


def disarm() -> None:
    global _armed
    _armed = False


def clear() -> None:
    global _plan, _armed, _hits
    _plan, _armed, _hits = None, False, 0
    fired.clear()
    _fail_plans.clear()


def link_fault_plan_from_env(var: str = "FFTPU_LINKFAULTS") -> dict:
    """Parse a link-fault plan — ``{edge: {fault: params}}``, the shape
    ``server/transport.FaultyTransport`` installs from — out of the
    environment. Entries are ``;``-separated ``edge:fault[@k=v,...]``;
    parameter values parse as floats. Empty/missing env = empty plan."""
    plan: dict[str, dict] = {}
    for entry in filter(None, (e.strip() for e
                               in os.environ.get(var, "").split(";"))):
        edge, _, rest = entry.partition(":")
        fault, _, params = rest.partition("@")
        kw: dict[str, float] = {}
        for pair in filter(None, (s.strip() for s in params.split(","))):
            key, _, val = pair.partition("=")
            kw[key.strip()] = float(val)
        plan.setdefault(edge.strip(), {})[fault.strip()] = kw
    return plan


def crashpoint(name: str) -> None:
    """Declare a named kill point. No plan installed = near-free."""
    global _hits
    if _plan is None:
        return
    fired[name] = fired.get(name, 0) + 1
    if not _armed or name != _plan[0]:
        return
    _hits += 1
    if _hits >= _plan[1]:
        # A REAL crash: no cleanup, no flushing, no thread joins — the
        # recovery story must not depend on any graceful-shutdown path.
        sys.stderr.write(f"crashpoint {name} hit {_hits}: killing\n")
        sys.stderr.flush()
        os._exit(KILL_EXIT_CODE)


def failpoint(name: str) -> None:
    """Declare a named injectable failure. With an armed plan for
    ``name``, raises :class:`InjectedFault` and burns one planned hit;
    otherwise (the production path) it is one dict truthiness check."""
    if not _fail_plans:
        return
    fired[name] = fired.get(name, 0) + 1
    if not _armed:
        return
    remaining = _fail_plans.get(name)
    if remaining is None:
        return
    if remaining <= 1:
        del _fail_plans[name]
    else:
        _fail_plans[name] = remaining - 1
    raise InjectedFault(f"injected fault at {name}")


install_from_env()
