"""Base utilities: telemetry, tracing, events, heaps, metrics, config.

Reference parity: common/lib/common-utils, packages/utils/telemetry-utils,
services-core/src/metricClient.ts, services-utils (nconf config).
"""

from .config import Config, default_config
from .events import BatchManager, Deferred, Heap, TypedEventEmitter
from .metrics import (
    STORM_STAGES,
    CountedLRU,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    StageLedger,
    default_registry,
)
from .telemetry import (
    ChildLogger,
    CollectingLogger,
    DebugLogger,
    MultiSinkLogger,
    NullLogger,
    PerformanceEvent,
    PerfTrace,
    TelemetryLogger,
    TraceSpans,
    timed,
)

__all__ = [
    "BatchManager",
    "ChildLogger",
    "CollectingLogger",
    "Config",
    "CountedLRU",
    "Counter",
    "DebugLogger",
    "Deferred",
    "default_config",
    "default_registry",
    "Gauge",
    "Heap",
    "Histogram",
    "MetricsRegistry",
    "MultiSinkLogger",
    "NullLogger",
    "PerformanceEvent",
    "PerfTrace",
    "StageLedger",
    "STORM_STAGES",
    "TelemetryLogger",
    "timed",
    "TraceSpans",
    "TypedEventEmitter",
]
