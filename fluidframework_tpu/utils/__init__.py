"""Base utilities: telemetry, tracing, events, heaps, config.

Reference parity: common/lib/common-utils, packages/utils/telemetry-utils.
"""
