"""Metrics registry: counters, gauges, latency histograms with p99.

Reference parity: services-core/src/metricClient.ts (server metric seam),
connectionTelemetry.ts (client op round-trip latency), merge-tree's
accumTime/localTime micro counters (client.ts:45-55). TPU addition: a
registry ``snapshot()`` is a flat dict of floats so per-chip snapshots can
be summed across a mesh with one ``psum``
(fluidframework_tpu.parallel.mesh.aggregate_metrics).

Thread safety: the storm serving stack touches one registry from several
threads (the bridge pump, the WAL writer's drain callbacks, fanout
harvest), so every mutator and ``snapshot()`` take a per-metric lock —
one uncontended ``threading.Lock`` per observe, not a global registry
lock a hot histogram would serialize the whole assembly on.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Any, Iterable


class Counter:
    """Monotonic event count (merged ops, ticks, nacks...)."""

    __slots__ = ("value", "_lock")

    def __init__(self) -> None:
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Gauge:
    """Point-in-time level (queue depth, resident docs...)."""

    __slots__ = ("value", "_lock")

    def __init__(self) -> None:
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def add(self, delta: float) -> None:
        with self._lock:
            self.value += delta


class Histogram:
    """Latency histogram over log-spaced buckets; O(1) observe, quantiles
    from linear interpolation within the winning bucket. Bounds default to
    1us..60s — wide enough for op-apply and device-tick latencies without
    per-sample storage (the "reservoir" the reference never needed because
    it never measured)."""

    __slots__ = ("_bounds", "_counts", "count", "total", "max", "min",
                 "_lock")

    def __init__(self, min_bound: float = 1e-6, max_bound: float = 60.0,
                 buckets_per_decade: int = 10) -> None:
        decades = math.log10(max_bound / min_bound)
        n = max(1, int(math.ceil(decades * buckets_per_decade)))
        self._bounds = [min_bound * (max_bound / min_bound) ** (i / n)
                        for i in range(1, n + 1)]
        self._counts = [0] * (n + 1)  # +1 overflow bucket
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self.min = math.inf
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        lo, hi = 0, len(self._bounds)
        while lo < hi:
            mid = (lo + hi) // 2
            if value <= self._bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        with self._lock:
            self.count += 1
            self.total += value
            if value > self.max:
                self.max = value
            if value < self.min:
                self.min = value
            self._counts[lo] += 1

    def quantile(self, q: float) -> float:
        with self._lock:
            if self.count == 0:
                return 0.0
            rank = q * self.count
            seen = 0
            for i, c in enumerate(self._counts):
                if c and seen + c >= rank:
                    if i >= len(self._bounds):
                        return self.max
                    # Linear interpolation within the winning bucket:
                    # its c samples are assumed uniform over (lo, hi].
                    bucket_lo = self._bounds[i - 1] if i > 0 else 0.0
                    bucket_hi = self._bounds[i]
                    frac = (rank - seen) / c
                    est = bucket_lo + frac * (bucket_hi - bucket_lo)
                    # Bucket edges can overshoot the true extremes
                    # (e.g. a single observation mid-bucket).
                    return min(max(est, self.min), self.max)
                seen += c
            return self.max

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0


def percentile(sorted_values, q: float):
    """Nearest-rank percentile (index ``ceil(q*n) - 1``) of an
    ascending-sorted sequence — THE one definition every small-sample
    decomposition in this repo uses (StageLedger.attribution,
    TraceSpans.hop_quantiles, the bench hop columns), so p99 of
    identical samples agrees across surfaces."""
    n = len(sorted_values)
    if n == 0:
        return 0.0
    idx = max(0, math.ceil(q * n) - 1)
    return sorted_values[min(n - 1, idx)]


#: Stage order of one storm serving tick — the fixed shape of every
#: :class:`StageLedger` record (server/storm.py fills these; the sum is
#: the attributable slice of the tick's wall clock).
STORM_STAGES = ("ingress_decode", "admission", "scatter", "device_dispatch",
                "readback", "wal_append", "wal_commit_wait", "ack_pack",
                "fanout_publish")


class StageLedger:
    """Per-tick stage attribution: ONE fixed-shape record per serving
    tick — tick id, queue depth, batch size, and a monotonic-ns split per
    pipeline stage — kept in a bounded ring buffer and mirrored into
    per-stage :class:`Histogram` s of a shared registry (so alfred's
    ``get_metrics`` exports ``<prefix>.<stage>.p50/p99`` and
    tools/monitor.py can render a live stage-attribution bar).

    The record dict is intentionally flat and identical every tick
    (stages absent from a split map record 0 ns), so downstream consumers
    (bench columns, the monitor bar) never branch on shape.

    Pipelined ticks (round 14) overlap stages across tick boundaries —
    tick N's ``wal_commit_wait`` runs on the WAL writer thread while
    tick N+1's ``device_dispatch`` runs on the serving thread — so the
    per-stage splits of one tick can SUM past the wall clock it
    occupied. ``record`` therefore also takes the tick's exclusive
    wall-clock slice (``wall_ns``, the harvest-to-harvest cadence) and
    the serving pipeline depth; :meth:`attribution` reports wall time
    and an explicit ``overlap_ms`` instead of pretending the concurrent
    spans were sequential.
    """

    def __init__(self, stages: Iterable[str] = STORM_STAGES,
                 registry: "MetricsRegistry | None" = None,
                 prefix: str = "storm.stage", capacity: int = 1024) -> None:
        self.stages = tuple(stages)
        self.prefix = prefix
        self._ring: deque = deque(maxlen=max(1, capacity))
        self._lock = threading.Lock()
        self._hists = None
        self._wall_hist = None
        if registry is not None:
            self._hists = {s: registry.histogram(f"{prefix}.{s}")
                           for s in self.stages}
            self._wall_hist = registry.histogram(f"{prefix}.wall")

    def record(self, tick_id: int, queue_depth: int, batch_docs: int,
               batch_ops: int, splits_ns: dict, wall_ns: int = 0,
               depth: int = 0) -> dict:
        """Commit one tick's record; unknown split keys are rejected
        (a typo'd stage would silently vanish from the attribution —
        and must fail under ``python -O`` too, hence no assert).
        ``wall_ns`` is the tick's exclusive wall-clock slice (0 =
        unknown, the pre-pipelining shape); ``depth`` the serving
        pipeline depth that produced it."""
        unknown = set(splits_ns) - set(self.stages)
        if unknown:
            raise ValueError(f"unknown ledger stages: {sorted(unknown)}")
        rec = {"tick": int(tick_id), "queue_depth": int(queue_depth),
               "batch_docs": int(batch_docs), "batch_ops": int(batch_ops),
               "wall": int(wall_ns), "depth": int(depth)}
        for s in self.stages:
            rec[s] = int(splits_ns.get(s, 0))
        with self._lock:
            self._ring.append(rec)
        if self._wall_hist is not None and rec["wall"] > 0:
            self._wall_hist.observe(rec["wall"] / 1e9)
        if self._hists is not None:
            for s in self.stages:
                ns = rec[s]
                if ns > 0:
                    self._hists[s].observe(ns / 1e9)
        return rec

    def amend(self, rec: dict, stage: str, ns: int) -> None:
        """Backfill one stage of an already-committed record — the WAL
        commit-wait completes ticks after the record is cut (acks drain
        at the durability watermark, not at harvest)."""
        if stage not in self.stages:
            raise ValueError(f"unknown ledger stage: {stage!r}")
        rec[stage] = int(ns)
        if self._hists is not None and ns > 0:
            self._hists[stage].observe(ns / 1e9)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self) -> None:
        """Drop the ring (benches clear warm-up/compile ticks so the
        attribution window covers only the measured run); the registry
        histograms keep their cumulative view."""
        with self._lock:
            self._ring.clear()

    def records(self) -> list[dict]:
        with self._lock:
            return [dict(r) for r in self._ring]

    def attribution(self) -> dict:
        """Per-stage share of attributed tick time over the ring window:
        {stage: {"share", "p50_ms", "p99_ms", "total_ms"}} plus a
        "_window" row (ticks covered, attributed vs total ns). The shares
        sum to 1.0 over stages with any time recorded. p50/p99 cover the
        ticks where the stage RAN (nonzero split) — the same population
        the registry histograms observe, so the two surfaces agree.

        When the records carry wall-clock slices (pipelined serving),
        each stage also reports ``of_wall`` — the fraction of real wall
        time it was active, which can sum PAST 1.0 across stages when
        they overlap — and "_window" reports the honest time budget:
        ``wall_ms`` (what the ticks actually occupied), ``overlap_ms``
        (attributed − wall, the concurrency the pipeline bought; 0 when
        stages ran sequentially) and ``pipeline_depth``. Summing the
        per-stage totals and calling it tick time double-counts under
        overlap — wall_ms is the denominator that does not lie."""
        recs = self.records()
        out: dict[str, Any] = {}
        if not recs:
            return {"_window": {"ticks": 0}}
        totals = {s: sum(r[s] for r in recs) for s in self.stages}
        grand = sum(totals.values()) or 1
        wall_total = sum(r.get("wall", 0) for r in recs)
        for s in self.stages:
            samples = sorted(r[s] for r in recs if r[s] > 0)
            out[s] = {
                "share": round(totals[s] / grand, 4),
                "p50_ms": round(percentile(samples, 0.50) / 1e6, 3),
                "p99_ms": round(percentile(samples, 0.99) / 1e6, 3),
                "total_ms": round(totals[s] / 1e6, 3),
            }
            if wall_total > 0:
                out[s]["of_wall"] = round(totals[s] / wall_total, 4)
        depths = [r.get("depth", 0) for r in recs if r.get("depth", 0) > 0]
        out["_window"] = {
            "ticks": len(recs),
            "attributed_ms": round(grand / 1e6, 3),
            "wall_ms": round(wall_total / 1e6, 3),
            "overlap_ms": round(max(0, grand - wall_total) / 1e6, 3)
            if wall_total > 0 else 0.0,
            "pipeline_depth": round(sum(depths) / len(depths), 2)
            if depths else 0,
            "mean_batch_docs": round(sum(r["batch_docs"] for r in recs)
                                     / len(recs), 1),
            "mean_queue_depth": round(sum(r["queue_depth"] for r in recs)
                                      / len(recs), 1),
        }
        return out


class CountedLRU:
    """Small bounded LRU map with hit/miss counters wired into a shared
    registry — the cache shape the serving hot path needs (storm cohort
    resolution, residency cold-handle lookups): O(1) get/put, strict
    entry bound, and an observable hit rate so a thrashing cache shows
    up in a metrics scrape instead of as unexplained tick time.

    NOT thread-safe by itself — callers on the serving thread use it
    bare; cross-thread users wrap it."""

    __slots__ = ("capacity", "_data", "_hits", "_misses")

    def __init__(self, capacity: int,
                 registry: "MetricsRegistry | None" = None,
                 prefix: str = "lru") -> None:
        from collections import OrderedDict
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._data: Any = OrderedDict()
        reg = registry if registry is not None else MetricsRegistry()
        self._hits = reg.counter(f"{prefix}.hits")
        self._misses = reg.counter(f"{prefix}.misses")

    def get(self, key, default=None):
        try:
            value = self._data[key]
        except KeyError:
            self._misses.inc()
            return default
        self._data.move_to_end(key)
        self._hits.inc()
        return value

    def put(self, key, value) -> None:
        data = self._data
        if key in data:
            data.move_to_end(key)
        data[key] = value
        if len(data) > self.capacity:
            data.popitem(last=False)  # evict least-recently-used

    def pop(self, key, default=None):
        return self._data.pop(key, default)

    def clear(self) -> None:
        self._data.clear()

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, key) -> bool:
        return key in self._data

    @property
    def hits(self) -> int:
        return int(self._hits.value)

    @property
    def misses(self) -> int:
        return int(self._misses.value)


class MetricsRegistry:
    """Named metric bag. ``snapshot()`` flattens to {name: float}; counters
    and gauges sum across shards, histograms export count/mean/p50/p99/max.
    Creation is locked; per-metric mutation locks live on the metrics."""

    def __init__(self) -> None:
        self._metrics: dict[str, Any] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, **kwargs: Any) -> Histogram:
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = Histogram(**kwargs)
            metric = self._metrics[name]
        assert isinstance(metric, Histogram), name
        return metric

    def _get(self, name: str, cls: type) -> Any:
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = cls()
            metric = self._metrics[name]
        assert isinstance(metric, cls), name
        return metric

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            metrics = list(self._metrics.items())
        out: dict[str, float] = {}
        for name, metric in metrics:
            if isinstance(metric, (Counter, Gauge)):
                out[name] = metric.value
            else:
                out[f"{name}.count"] = float(metric.count)
                out[f"{name}.mean"] = metric.mean
                out[f"{name}.p50"] = metric.quantile(0.50)
                out[f"{name}.p99"] = metric.quantile(0.99)
                out[f"{name}.max"] = metric.max
        return out


default_registry = MetricsRegistry()
