"""Metrics registry: counters, gauges, latency histograms with p99.

Reference parity: services-core/src/metricClient.ts (server metric seam),
connectionTelemetry.ts (client op round-trip latency), merge-tree's
accumTime/localTime micro counters (client.ts:45-55). TPU addition: a
registry ``snapshot()`` is a flat dict of floats so per-chip snapshots can
be summed across a mesh with one ``psum``
(fluidframework_tpu.parallel.mesh.aggregate_metrics).
"""

from __future__ import annotations

import math
from typing import Any


class Counter:
    """Monotonic event count (merged ops, ticks, nacks...)."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount


class Gauge:
    """Point-in-time level (queue depth, resident docs...)."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)

    def add(self, delta: float) -> None:
        self.value += delta


class Histogram:
    """Latency histogram over log-spaced buckets; O(1) observe, quantiles
    from bucket interpolation. Bounds default to 1us..60s — wide enough for
    op-apply and device-tick latencies without per-sample storage (the
    "reservoir" the reference never needed because it never measured)."""

    __slots__ = ("_bounds", "_counts", "count", "total", "max")

    def __init__(self, min_bound: float = 1e-6, max_bound: float = 60.0,
                 buckets_per_decade: int = 10) -> None:
        decades = math.log10(max_bound / min_bound)
        n = max(1, int(math.ceil(decades * buckets_per_decade)))
        self._bounds = [min_bound * (max_bound / min_bound) ** (i / n)
                        for i in range(1, n + 1)]
        self._counts = [0] * (n + 1)  # +1 overflow bucket
        self.count = 0
        self.total = 0.0
        self.max = 0.0

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        if value > self.max:
            self.max = value
        lo, hi = 0, len(self._bounds)
        while lo < hi:
            mid = (lo + hi) // 2
            if value <= self._bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        self._counts[lo] += 1

    def quantile(self, q: float) -> float:
        if self.count == 0:
            return 0.0
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self._counts):
            seen += c
            if seen >= rank:
                if i >= len(self._bounds):
                    return self.max
                # A bucket's upper bound can overshoot the true maximum.
                return min(self._bounds[i], self.max)
        return self.max

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0


class MetricsRegistry:
    """Named metric bag. ``snapshot()`` flattens to {name: float}; counters
    and gauges sum across shards, histograms export count/mean/p50/p99/max."""

    def __init__(self) -> None:
        self._metrics: dict[str, Any] = {}

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, **kwargs: Any) -> Histogram:
        if name not in self._metrics:
            self._metrics[name] = Histogram(**kwargs)
        metric = self._metrics[name]
        assert isinstance(metric, Histogram), name
        return metric

    def _get(self, name: str, cls: type) -> Any:
        if name not in self._metrics:
            self._metrics[name] = cls()
        metric = self._metrics[name]
        assert isinstance(metric, cls), name
        return metric

    def snapshot(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for name, metric in self._metrics.items():
            if isinstance(metric, (Counter, Gauge)):
                out[name] = metric.value
            else:
                out[f"{name}.count"] = float(metric.count)
                out[f"{name}.mean"] = metric.mean
                out[f"{name}.p50"] = metric.quantile(0.50)
                out[f"{name}.p99"] = metric.quantile(0.99)
                out[f"{name}.max"] = metric.max
        return out


default_registry = MetricsRegistry()
