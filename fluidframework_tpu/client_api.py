"""Legacy client-api — the one-call "document" facade.

Reference parity: packages/runtime/client-api/src/api/document.ts:58
(``Document`` bundling loader + runtime + a root map behind
``load()``/``createMap()``/``createString()``/``getRoot()``) — the
deprecated-but-shipped convenience layer predating aqueduct/fluid-static.
Kept for surface parity: a user porting old client-api code finds the
same verbs here, implemented over the modern Loader/Container stack.
"""

from __future__ import annotations

import uuid
from typing import Callable

from .dds.cell import SharedCell
from .dds.directory import SharedDirectory
from .dds.ink import Ink
from .dds.map import SharedMap
from .dds.matrix import SharedMatrix
from .dds.sequence import SharedString
from .drivers.base import DocumentService
from .runtime.container import Container

_ROOT_STORE = "root"
_ROOT_MAP = "root"


class Document:
    """Loader + runtime + root map in one object (document.ts:58)."""

    def __init__(self, container: Container, existing: bool) -> None:
        self.container = container
        self._existing = existing
        datastore = container.runtime.get_datastore(_ROOT_STORE)
        self._datastore = datastore

    # -- accessors (document.ts getRoot/existing) -----------------------------

    def get_root(self) -> SharedMap:
        return self._datastore.get_channel(_ROOT_MAP)

    @property
    def existing(self) -> bool:
        """True when the document pre-existed this session (loaded, not
        created here) — the reference client-api's existing flag."""
        return self._existing

    # -- creators (document.ts createMap/createString/...) --------------------

    def _create(self, channel_type: str):
        # Channel ids must be globally unique — a per-session counter
        # collides across sessions/clients (document.ts uses uuid()).
        name = f"channel-{uuid.uuid4().hex}"
        return self._datastore.create_channel(name, channel_type)

    def create_map(self) -> SharedMap:
        return self._create(SharedMap.channel_type)

    def create_directory(self) -> SharedDirectory:
        return self._create(SharedDirectory.channel_type)

    def create_string(self) -> SharedString:
        return self._create(SharedString.channel_type)

    def create_cell(self) -> SharedCell:
        return self._create(SharedCell.channel_type)

    def create_matrix(self) -> SharedMatrix:
        return self._create(SharedMatrix.channel_type)

    def create_ink(self) -> Ink:
        return self._create(Ink.channel_type)

    def close(self) -> None:
        self.container.close()


def create(service: DocumentService) -> Document:
    """Create a new document with a root map and attach it."""
    container = Container.create_detached(service)
    datastore = container.runtime.create_datastore(_ROOT_STORE)
    datastore.create_channel(_ROOT_MAP, SharedMap.channel_type)
    container.attach()
    return Document(container, existing=False)


def load(service_factory: Callable[[str], DocumentService],
         doc_id: str) -> Document:
    """Open an existing document (client-api load(): resolve + request)."""
    container = Container.load(service_factory(doc_id))
    return Document(container, existing=True)
