"""Orderer seam — the producer boundary between the front door and deli.

Reference parity: server/routerlicious/packages/kafka-orderer
(kafkaOrderer.ts:17 ``KafkaOrderer``/``KafkaOrdererConnection``: a per-
(document, client) connection whose ``order(messages)`` produces raw ops
into the ordering topic) and services-core's IOrderer/IOrdererConnection
seam. The bus behind it is pluggable — the in-memory Python bus, the
durable file bus, or the C++ shuttle (native_bus) — which is exactly the
point of the seam: alfred orders ops without knowing the transport.
"""

from __future__ import annotations

from .bus import MessageBus
from .sequencer import RawOperation

RAWDELTAS = "rawdeltas"


class OrdererConnection:
    """One (document, client) ordering lane (KafkaOrdererConnection)."""

    def __init__(self, orderer: "BusOrderer", doc_id: str,
                 client_id: str | None) -> None:
        self._orderer = orderer
        self.doc_id = doc_id
        self.client_id = client_id

    def order(self, raws: list[RawOperation]) -> None:
        """Produce raw operations into the ordering topic; per-document
        FIFO holds because the topic partitions by doc id."""
        for raw in raws:
            self._orderer.bus.produce(self._orderer.topic, self.doc_id, raw)


class BusOrderer:
    """IOrderer over any MessageBus-shaped transport (KafkaOrderer)."""

    def __init__(self, bus: MessageBus, topic: str = RAWDELTAS) -> None:
        self.bus = bus
        self.topic = topic

    def connect(self, doc_id: str,
                client_id: str | None = None) -> OrdererConnection:
        return OrdererConnection(self, doc_id, client_id)

    def order_system(self, doc_id: str, raw: RawOperation) -> None:
        """Service-originated control ops (join/leave) — no client lane."""
        self.bus.produce(self.topic, doc_id, raw)
