"""KernelSequencerHost — the batched device sequencer behind the service.

Reference parity: this replaces the per-partition deli lambda fleet
(server/routerlicious/packages/lambdas/src/deli/lambda.ts + the
lambdas-driver partition manager) with ONE device-resident state batch:
every document is a row of :class:`fluidframework_tpu.ops.sequencer.
SequencerState`, and a service tick sequences the pending ops of all
documents in a single ``process_batch`` call (vmap over the document axis —
the workload's data-parallel axis, SURVEY.md §2.9).

The host owns everything the kernel cannot: the ``doc_id`` → state-row and
``client_id`` → slot mappings (deli's ClientSequenceNumberManager keys by
string id; the kernel keys by slot index), checkpoint encode/decode, and
idle-client ejection (deli checkIdleClients). Every ticket outcome —
including NACKs for clients the kernel has never seen — is decided BY the
kernel: the host allocates a slot for any referenced client id so the op can
be expressed on device, then prunes allocations that did not result in an
active client. This keeps mid-tick ordering exact (a NACK after a sequenced
op in the same tick reports the post-op seq/msn, as the scalar path does).

Two call paths:

- :meth:`sequence` — synchronous per-op path used by the in-proc server
  (one-op device batch; correct, not fast).
- :meth:`submit` + :meth:`flush` — the throughput path: queue raw ops per
  document, then sequence every document's tick in one device call.

Both produce tickets identical to the scalar
:class:`fluidframework_tpu.server.sequencer.DocumentSequencer` (differential
fuzz in tests/test_kernel_host.py).
"""

from __future__ import annotations

import functools

import jax
import numpy as np

from ..ops import opcodes as oc
from ..ops import sequencer as seqk
from ..ops import sequencer_pallas as seqp
from ..protocol.messages import MessageType
from ..utils import compile_cache
from .sequencer import (
    DocumentSequencer,
    RawOperation,
    SequencerCheckpoint,
    Ticket,
)


@functools.partial(jax.jit, donate_argnums=(0,))
def _step_one(state: seqk.SequencerState, row, ops: seqk.OpBatch):
    """Sequence a [1, K] op batch against state row ``row`` in place."""
    sliced = jax.tree.map(
        lambda a: jax.lax.dynamic_slice_in_dim(a, row, 1, axis=0), state)
    new_row, out = seqk.process_batch(sliced, ops)
    state = jax.tree.map(
        lambda a, r: jax.lax.dynamic_update_slice_in_dim(a, r, row, axis=0),
        state, new_row)
    return state, out


# Donated + repeatedly executed: must never load from the persistent
# cache (jaxlib 0.4.37 double-frees donated buffers on the second run of
# a cache-deserialized executable — compile_cache.bypass docstring).
_step_one = compile_cache.uncached(_step_one)


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _tick_k(max_pending: int) -> int:
    """Op-batch width for a flush tick: pow2-bucketed with a floor of 32.
    Padding a short tick with invalid ops costs a few masked scan steps;
    compiling a fresh device program per tiny k costs seconds — the floor
    keeps the shape set at {32, 64, 128, ...} across every flush path."""
    return max(32, _next_pow2(max_pending))


class KernelSequencerHost:
    """Device-batched total-order sequencer for many documents.

    The device state carries ``num_slots`` allocatable client lanes plus one
    reserved GHOST lane (the last index) that is never joined: ops that
    reference a client id the host cannot map (unknown client while every
    lane is taken) are encoded against the ghost lane, which is permanently
    inactive, so the kernel itself produces the NACK_NONEXISTENT_CLIENT /
    dup-leave-IGNORED outcome with exact mid-tick ordering. Joins that find
    no free lane grow the slot axis (doubling), mirroring deli's unbounded
    per-document client table.
    """

    DEFAULT_TIMEOUT_MS = 5 * 60 * 1000

    def __init__(self, num_slots: int = 16, initial_capacity: int = 8) -> None:
        self._alloc_slots = max(1, num_slots)  # lanes handed to real clients
        self._capacity = max(1, initial_capacity)
        self._state = seqk.init_state(self._capacity, self._alloc_slots + 1)
        self._rows: dict[str, int] = {}
        # Row recycling (the doc-residency seam): released rows return to
        # this free list and are reissued before the high-water counter
        # advances, so device capacity is bounded by the PEAK RESIDENT doc
        # count — never the total number of documents ever served.
        self._free_rows: list[int] = []
        self._row_count = 0  # high-water mark of allocated rows
        self._slots: list[dict[str, int]] = [{} for _ in range(self._capacity)]
        # Bumped on every client->slot membership change; callers caching
        # resolved (row, slot) cohorts key on it (server/storm.py).
        self.membership_gen = 0
        self._pending: list[list[RawOperation]] = [
            [] for _ in range(self._capacity)]
        self._timeout_ms: list[int] = [
            self.DEFAULT_TIMEOUT_MS] * self._capacity
        self._doc_counter = 0
        # Tickets produced by an internal flush (a sync sequence() call may
        # not jump the total order, so it flushes pending ops first) buffer
        # here until the next flush() caller collects them — nothing is
        # ever sequenced-and-dropped.
        self._ready: dict[str, list[Ticket]] = {}
        # Host mirror of the device state, fetched in ONE transfer and
        # reused until the next device write: per-document checkpoint()
        # calls must not each pay a device readback (a tunneled TPU
        # attachment charges ~100ms per round trip — 10k docs would turn
        # one pump into 10k RTTs).
        self._host_state = None

    @property
    def _ghost(self) -> int:
        return self._alloc_slots

    # -- document / slot management -------------------------------------------

    def _row(self, doc_id: str) -> int:
        row = self._rows.get(doc_id)
        if row is None:
            if self._free_rows:
                row = self._free_rows.pop()
            else:
                row = self._row_count
                if row >= self._capacity:
                    self._grow_rows()
                self._row_count += 1
            self._rows[doc_id] = row
        return row

    def release_doc(self, doc_id: str) -> int:
        """Free a document's device row (the eviction half of tiered doc
        residency): blank the row back to init defaults on device and
        recycle its index. The caller owns durability — a released row's
        state is GONE from this host, so evict only after its checkpoint
        (sequencer checkpoint + WAL watermark) is durable. Returns the
        freed row index."""
        row = self._rows.pop(doc_id)
        assert not self._pending[row], (
            f"release_doc({doc_id!r}) with pending ops — flush first")
        self._ready.pop(doc_id, None)
        self._slots[row] = {}
        self._timeout_ms[row] = self.DEFAULT_TIMEOUT_MS
        # Cohort caches key on the membership generation; a recycled row
        # must never be served through a stale (row, slot) resolution.
        self.membership_gen += 1
        blank = seqk.init_state(1, self._alloc_slots + 1)
        self._state = seqk.SequencerState(
            **{f: getattr(self._state, f).at[row].set(
                getattr(blank, f)[0]) for f in self._state._fields})
        self._host_state = None
        self._free_rows.append(row)
        return row

    @property
    def resident_docs(self) -> int:
        return len(self._rows)

    def _grow_rows(self) -> None:
        old = self._capacity
        self._capacity = old * 2
        pad = lambda a: np.pad(np.asarray(a),
                               [(0, old)] + [(0, 0)] * (a.ndim - 1))
        grown = seqk.SequencerState(
            **{f: pad(getattr(self._state, f))
               for f in self._state._fields})
        # Padded rows must match init defaults (cevict inits True).
        grown.cevict[old:] = True
        self._state = jax.device_put(grown)
        self._host_state = None
        self._slots += [{} for _ in range(old)]
        self._pending += [[] for _ in range(old)]
        self._timeout_ms += [self.DEFAULT_TIMEOUT_MS] * old

    def _grow_slots(self, need: int) -> None:
        """Double the allocatable slot axis until ``need`` lanes fit. The old
        ghost lane is recycled as allocatable (the kernel never writes an
        inactive un-joined lane, so its state is pristine zeros)."""
        new_alloc = self._alloc_slots
        while new_alloc < need:
            new_alloc *= 2
        extra = (new_alloc + 1) - (self._alloc_slots + 1)
        pad2 = lambda a: (np.pad(np.asarray(a), [(0, 0), (0, extra)])
                          if a.ndim == 2 else np.asarray(a))
        grown = seqk.SequencerState(
            **{f: pad2(getattr(self._state, f))
               for f in self._state._fields})
        grown.cevict[:, self._alloc_slots + 1:] = True
        self._state = jax.device_put(grown)
        self._host_state = None
        self._alloc_slots = new_alloc

    def _slot_for(self, row: int, client_id: str, fresh: set[str],
                  allow_ghost: bool) -> int:
        slots = self._slots[row]
        if client_id in slots:
            return slots[client_id]
        used = set(slots.values())
        for s in range(self._alloc_slots):
            if s not in used:
                slots[client_id] = s
                fresh.add(client_id)
                return s
        if allow_ghost:
            # Unknown client, no lane free: the permanently-inactive ghost
            # lane yields the kernel's nonexistent-client outcome without
            # allocating (and without a mapping to prune).
            return self._ghost
        self._grow_slots(len(used) + 1)
        for s in range(self._alloc_slots):
            if s not in used:
                slots[client_id] = s
                fresh.add(client_id)
                return s
        raise AssertionError("slot growth failed to free a lane")

    @staticmethod
    def _referenced_client(raw: RawOperation) -> str | None:
        if raw.client_id is not None:
            return raw.client_id
        if raw.type == MessageType.CLIENT_JOIN:
            return getattr(raw.data, "client_id", raw.data)
        if raw.type == MessageType.CLIENT_LEAVE:
            return raw.data
        return None

    # -- encode / decode --------------------------------------------------------

    def _encode(self, row: int, raw: RawOperation, fresh: set[str]) -> dict:
        if raw.client_id is None:
            if raw.type in (MessageType.CLIENT_JOIN, MessageType.CLIENT_LEAVE):
                target = self._slot_for(
                    row, self._referenced_client(raw), fresh,
                    allow_ghost=raw.type == MessageType.CLIENT_LEAVE)
                return dict(kind=int(raw.type), slot=-1, target=target,
                            timestamp=raw.timestamp,
                            can_summarize=raw.can_summarize,
                            can_evict=raw.can_evict)
            is_nack_future = (isinstance(raw.contents, dict)
                              and raw.contents.get("type") == "nackFuture")
            return dict(kind=int(raw.type), slot=-1,
                        timestamp=raw.timestamp,
                        has_contents=raw.contents is not None,
                        is_nack_future=is_nack_future)
        return dict(kind=int(raw.type),
                    slot=self._slot_for(row, raw.client_id, fresh,
                                        allow_ghost=True),
                    client_seq=raw.client_seq, ref_seq=raw.ref_seq,
                    timestamp=raw.timestamp,
                    has_contents=raw.contents is not None)

    def _decode_doc(self, row: int, raws: list[RawOperation],
                    encs: list[dict], out, d: int,
                    fresh: set[str]) -> list[Ticket]:
        """Decode one document's tickets and settle its slot mappings."""
        tickets = []
        joined_ok: set[str] = set()
        for i, (raw, enc) in enumerate(zip(raws, encs)):
            kind = int(out.kind[d, i])
            tickets.append(Ticket(
                kind=kind,
                seq=int(out.seq[d, i]),
                msn=int(out.msn[d, i]),
                send=int(out.send[d, i]) if kind == oc.OUT_SEQUENCED
                else oc.SEND_IMMEDIATE,
                nack_code=int(out.nack_code[d, i]),
                op=raw,
            ))
            if raw.client_id is None and raw.type == MessageType.CLIENT_LEAVE:
                if kind == oc.OUT_SEQUENCED:
                    self._slots[row].pop(raw.data, None)
                    self.membership_gen += 1
                    joined_ok.discard(raw.data)
            elif raw.client_id is None and raw.type == MessageType.CLIENT_JOIN:
                # A sequenced join activates the lane; a dup-join (IGNORED)
                # still upserts the client on device (ops.sequencer
                # join_mask), so the lane is live either way. Re-adding here
                # also restores the mapping after a leave→rejoin of the same
                # client within one tick (the leave popped it above).
                if kind in (oc.OUT_SEQUENCED, oc.OUT_IGNORED):
                    client_id = getattr(raw.data, "client_id", raw.data)
                    self._slots[row][client_id] = enc["target"]
                    self.membership_gen += 1
                    joined_ok.add(client_id)
        # Prune allocations that never became an active client: their slot
        # is inactive on device, so keeping the mapping would leak slots.
        for client_id in fresh:
            if client_id not in joined_ok:
                self._slots[row].pop(client_id, None)
                self.membership_gen += 1
        return tickets

    @staticmethod
    def _check_timestamp(raw: RawOperation) -> None:
        """Reject out-of-range timestamps BEFORE any host state mutates: a
        poisoned op must fail its own submit, not wedge a later flush of
        every document (timestamps are i32 ms since service start)."""
        if not 0 <= raw.timestamp < 2**31:
            raise ValueError(
                f"timestamp {raw.timestamp} out of i32 range — timestamps "
                "are milliseconds since service start, not epoch ms")

    # -- synchronous per-op path ----------------------------------------------

    def sequence(self, doc_id: str, raw: RawOperation) -> Ticket:
        self._check_timestamp(raw)
        row = self._row(doc_id)
        if self._pending[row]:
            # Ops queued for the batched path must sequence first — a sync
            # call may not jump the document's total order. Their tickets
            # stay buffered in _ready for the next flush() caller.
            self._flush_pending()
        fresh: set[str] = set()
        enc = self._encode(row, raw, fresh)
        ops = seqk.make_op_batch([[enc]], 1, 1)
        self._state, out = _step_one(self._state, row, ops)
        self._host_state = None
        out = jax.tree.map(np.asarray, out)
        return self._decode_doc(row, [raw], [enc], out, 0, fresh)[0]

    # -- batched tick path ------------------------------------------------------

    def submit(self, doc_id: str, raw: RawOperation) -> None:
        self._check_timestamp(raw)
        self._pending[self._row(doc_id)].append(raw)

    def flush(self) -> dict[str, list[Ticket]]:
        """Sequence every document's pending ops in one device call and
        return them, together with any tickets buffered by an internal
        flush since the last call."""
        self._flush_pending()
        out, self._ready = self._ready, {}
        return out

    def _flush_pending(self) -> None:
        doc_ids = [d for d in self._rows if self._pending[self._rows[d]]]
        if not doc_ids:
            return
        per_doc_ops = [[] for _ in range(self._capacity)]
        fresh_by_doc: dict[str, set[str]] = {}
        max_k = 1
        for doc_id in doc_ids:
            row = self._rows[doc_id]
            fresh: set[str] = set()
            per_doc_ops[row] = [self._encode(row, raw, fresh)
                                for raw in self._pending[row]]
            fresh_by_doc[doc_id] = fresh
            max_k = max(max_k, len(per_doc_ops[row]))
        ops = seqk.make_op_batch(per_doc_ops, self._capacity,
                                 _next_pow2(max_k))
        self._state, out = seqp.process_batch_best(self._state, ops)
        self._host_state = None
        # One transfer for the whole tick: the per-op decode below
        # must index HOST arrays, not a device buffer (each device
        # index would be a tunnel round trip).
        out = jax.tree.map(np.asarray, out)
        for doc_id in doc_ids:
            row = self._rows[doc_id]
            self._ready.setdefault(doc_id, []).extend(self._decode_doc(
                row, self._pending[row], per_doc_ops[row], out, row,
                fresh_by_doc[doc_id]))
            self._pending[row] = []

    # -- idle ejection (deli checkIdleClients) ---------------------------------

    def idle_clients(self, now: int,
                     timeout_ms: int | None = None
                     ) -> list[tuple[str, str]]:
        """(doc_id, client_id) pairs idle past the timeout; the service
        injects CLIENT_LEAVE for each (alfred does this in the reference).
        Without an override, each document's own timeout applies (it
        survives checkpoint/restore, like the scalar sequencer's)."""
        out = []
        masks: dict[int, np.ndarray] = {}
        for doc_id, row in self._rows.items():
            t = timeout_ms if timeout_ms is not None else self._timeout_ms[row]
            if t not in masks:
                masks[t] = np.asarray(seqk.find_idle(self._state, now, t))
            for client_id, slot in self._slots[row].items():
                if masks[t][row, slot]:
                    out.append((doc_id, client_id))
        return out

    # -- checkpoint / restore ---------------------------------------------------

    def _host_view(self):
        """Full host copy of the device state (one transfer, cached until
        the next device write)."""
        if self._host_state is None:
            self._host_state = jax.tree.map(np.asarray, self._state)
        return self._host_state

    def checkpoint(self, doc_id: str,
                   log_offset: int = -1) -> SequencerCheckpoint:
        """Read one document's row from the cached host mirror into the
        durable checkpoint format shared with the scalar sequencer (deli
        checkpointContext)."""
        row = self._rows[doc_id]
        s = jax.tree.map(lambda a: a[row], self._host_view())
        clients = []
        for client_id, slot in sorted(self._slots[row].items()):
            if not bool(s.active[slot]):
                continue
            clients.append({
                "client_id": client_id,
                "client_seq": int(s.cseq[slot]),
                "ref_seq": int(s.cref[slot]),
                "last_update": int(s.clu[slot]),
                "can_evict": bool(s.cevict[slot]),
                "can_summarize": bool(s.csum[slot]),
                "nack": bool(s.cnack[slot]),
            })
        return SequencerCheckpoint(
            sequence_number=int(s.seq),
            minimum_sequence_number=int(s.msn),
            last_sent_msn=int(s.last_sent_msn),
            no_active_clients=not any(np.asarray(s.active)),
            clients=clients,
            nack_future=bool(s.nack_future),
            client_timeout_ms=self._timeout_ms[row],
            log_offset=log_offset,
        )

    def checkpoint_all(self) -> dict[str, SequencerCheckpoint]:
        """Checkpoints for EVERY tracked document off the cached host
        mirror — one device transfer however many documents (the storm
        snapshot path; per-doc checkpoint() in a loop would be O(docs)
        cache probes but this makes the intent explicit and skips the
        per-call row slicing overhead)."""
        self._host_view()
        return {doc_id: self.checkpoint(doc_id) for doc_id in self._rows}

    def restore(self, doc_id: str, cp: SequencerCheckpoint) -> None:
        """Load a checkpoint into a document row, OVERWRITING any live row
        for the document: the checkpoint + committed bus offset are the
        consistent pair, and a stale row from a prior service life (its
        post-checkpoint ops will replay from the bus) must not survive.
        Writes only the target row on device (no full-state round-trip)."""
        if len(cp.clients) > self._alloc_slots:
            self._grow_slots(len(cp.clients))
        row = self._row(doc_id)
        self._slots[row] = {}
        self.membership_gen += 1
        self._pending[row] = []
        self._ready.pop(doc_id, None)
        self._timeout_ms[row] = cp.client_timeout_ms
        lanes = self._alloc_slots + 1
        vals = dict(
            seq=np.int32(cp.sequence_number),
            msn=np.int32(cp.minimum_sequence_number),
            last_sent_msn=np.int32(cp.last_sent_msn),
            nack_future=np.bool_(cp.nack_future),
            active=np.zeros(lanes, np.bool_),
            cseq=np.zeros(lanes, np.int32),
            cref=np.zeros(lanes, np.int32),
            clu=np.zeros(lanes, np.int32),
            csum=np.zeros(lanes, np.bool_),
            cnack=np.zeros(lanes, np.bool_),
            cevict=np.ones(lanes, np.bool_),
        )
        for slot, c in enumerate(cp.clients):
            self._slots[row][c["client_id"]] = slot
            vals["active"][slot] = True
            vals["cseq"][slot] = c["client_seq"]
            vals["cref"][slot] = c["ref_seq"]
            vals["clu"][slot] = c["last_update"]
            vals["csum"][slot] = c["can_summarize"]
            vals["cnack"][slot] = c["nack"]
            vals["cevict"][slot] = c["can_evict"]
        self._state = seqk.SequencerState(
            **{f: getattr(self._state, f).at[row].set(vals[f])
               for f in self._state._fields})
        self._host_state = None

    # -- LocalCollabServer integration -----------------------------------------

    def document_factory(self):
        """A ``sequencer_factory`` for LocalCollabServer: each new document
        gets an adapter routing tickets through this host's device batch."""
        def factory() -> "KernelDocumentSequencer":
            doc_id = f"kernel-doc-{self._doc_counter}"
            self._doc_counter += 1
            return KernelDocumentSequencer(self, doc_id)
        return factory


class KernelDocumentSequencer:
    """Per-document adapter with the DocumentSequencer.ticket interface."""

    def __init__(self, host: KernelSequencerHost, doc_id: str) -> None:
        self._host = host
        self._doc_id = doc_id

    def ticket(self, raw: RawOperation) -> Ticket:
        return self._host.sequence(self._doc_id, raw)

    def checkpoint(self, log_offset: int = -1) -> SequencerCheckpoint:
        return self._host.checkpoint(self._doc_id, log_offset)


__all__ = [
    "KernelSequencerHost",
    "KernelDocumentSequencer",
    "DocumentSequencer",
]
