"""Networked replication transport: the wire under the plane.

PR 19/20 built the whole HA story — quorum-shipped WAL batches,
replicated head flips, read replicas tailing a follower — over ONE
in-process seam: ``ReplicaLink.call()`` was a direct method call that
could never time out, partition, reorder or duplicate. This module
cuts that cord (ROADMAP items 1–3):

* :class:`ReplicaServer` / :class:`ReplicaServerThread` — host a
  :class:`~.replication.ReplicaNode` behind an asyncio TCP listener.
  The framing is ``server/alfred.py``'s: a 4-byte big-endian length
  prefix, ``MAX_FRAME``-bounded, one response frame per request frame.
  Replication frames (storm-codec bodies) carry ``ReplicaNode.on_frame``
  BYTE-FOR-BYTE — the gap/dup/nack stream protocol, the version stamps
  and the incarnation fencing are the same bytes whether the follower
  is a local object or another OS process. JSON bodies are control
  frames (``hello``, ``ping``, ``shutdown`` + caller-registered verbs —
  the read-replica children register ``read_at``/``get_deltas`` here,
  so the ``ReplicaDirectory`` reads ride the same socket).
* :class:`NetworkReplicaLink` — the client half, a drop-in for
  ``ReplicaLink`` (same ``call(frame) -> header`` contract, so
  ``ReplicationPlane`` needs no transport-specific code): blocking
  socket per link, per-call deadline, bounded retries with exponential
  backoff + decorrelated jitter, transparent reconnection. Retrying a
  frame is safe BECAUSE the replica protocol is idempotent (dup
  delivery acks, gaps nack into the leader's resync) — the transport
  leans on the stream protocol instead of duplicating its sequencing.
* :class:`FaultyTransport` — a seeded, deterministic link-fault
  injector in the spirit of ``utils/faults.py`` crashpoints: named
  faults (``drop``, ``delay``, ``dup``, ``reorder``, ``slow``,
  ``partition``, ``partition_send``, ``partition_recv``) installable
  per edge from a plan, so the chaos harness drives real network
  pathology — not just ``kill -9``.

Failure detection rides the same frames: every successful call renews
the link's lease on the leader (``ReplicationPlane.heartbeat`` probes
idle links and trips ``quorum_ok`` when fewer than ``acks_required``
leases are fresh), and every inbound frame stamps the FOLLOWER's
``last_frame_monotonic`` (surfaced as ``leader_silence_s`` in
``hello`` — the promotion-eligibility signal a cluster harness polls).
"""

from __future__ import annotations

import asyncio
import json
import random
import socket
import threading
import time

from ..protocol.codec import frame_body, is_storm_body
from .alfred import read_frame_raw_sync
from .replication import ReplicationLinkDown, _frame

#: Per-call socket deadline (connect and round trip alike). Generous:
#: a follower fsync under load is milliseconds; seconds means dead.
DEFAULT_CALL_TIMEOUT_S = 5.0

#: Bounded retransmits per call — each retry reconnects, so a bounced
#: follower process is transparently redialed mid-stream.
DEFAULT_RETRIES = 3

#: Exponential backoff base/cap between retransmits. Jitter is
#: multiplicative in [0.5, 1.5) from the link's own seeded RNG, so a
#: partition healing under N leaders does not produce N synchronized
#: retry storms.
BACKOFF_BASE_S = 0.05
BACKOFF_MAX_S = 1.0

#: Failure-detector defaults (``ReplicationPlane.start_failure_detector``):
#: probe cadence and the lease a silent follower holds before it stops
#: counting toward the quorum.
HEARTBEAT_INTERVAL_S = 0.5
LEASE_S = 2.0

#: Installable link faults (see :class:`FaultyTransport`). ``partition``
#: drops both directions; ``partition_send`` loses requests (the
#: follower never sees the frame); ``partition_recv`` delivers the
#: frame but loses the response (the asymmetric half — the follower's
#: state advances while the leader counts a failure and retries, the
#: duplicate-delivery path exercised for real).
LINK_FAULTS = ("drop", "delay", "dup", "reorder", "slow",
               "partition", "partition_send", "partition_recv")


def _percentile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1)))
    return sorted_vals[idx]


# -- server half ---------------------------------------------------------------


class ReplicaServer:
    """Serve one :class:`ReplicaNode` over asyncio TCP. Storm-codec
    bodies dispatch to ``node.on_frame`` (off the event loop — a
    follower fsync must not stall another connection's heartbeat);
    JSON bodies dispatch to control handlers. Built-in controls:

    * ``hello`` — ``{node_id, len, hseq, heads, role, incarnation,
      leader_silence_s}``: the link handshake AND the promotion-
      eligibility scrape (``leader_silence_s`` past the lease means
      this follower stopped hearing from its leader).
    * ``ping`` — liveness, no node state touched.
    * ``shutdown`` — close the node (releasing its WAL — the step a
      cluster harness takes before promoting this directory) and stop
      serving.

    Extra verbs come from ``handlers`` (``name -> callable(dict) ->
    dict``) — the read-replica child registers its read surface here.
    """

    def __init__(self, node, host: str = "127.0.0.1", port: int = 0,
                 handlers: dict | None = None) -> None:
        self.node = node
        self.host = host
        self.port = port
        self.handlers = dict(handlers or {})
        self._server: asyncio.AbstractServer | None = None
        self._shutdown = asyncio.Event()
        self.stats = {"frames": 0, "control": 0, "bad_frames": 0}

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, host=self.host, port=self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def serve_until_shutdown(self) -> None:
        await self._shutdown.wait()
        self.close()

    def close(self) -> None:
        if self._server is not None:
            self._server.close()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        from .alfred import read_frame_raw
        try:
            while True:
                body = await read_frame_raw(reader)
                if is_storm_body(body):
                    self.stats["frames"] += 1
                    resp = await asyncio.to_thread(
                        self.node.on_frame, bytes(body))
                else:
                    self.stats["control"] += 1
                    resp = await self._control(bytes(body))
                writer.write(frame_body(resp))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            writer.close()

    async def _control(self, body: bytes) -> bytes:
        try:
            req = json.loads(body.decode())
            op = req.get("op")
            if op == "ping":
                out = {"ok": True}
            elif op == "hello":
                out = self._hello()
            elif op == "shutdown":
                await asyncio.to_thread(self.node.close)
                out = {"ok": True, "closed": True}
                self._shutdown.set()
            elif op in self.handlers:
                out = await asyncio.to_thread(self.handlers[op], req)
            else:
                out = {"error": f"unknown op {op!r}"}
        except Exception as err:  # a broken verb must not kill the link
            self.stats["bad_frames"] += 1
            out = {"error": f"{type(err).__name__}: {err}"}
        return json.dumps(out).encode()

    def _hello(self) -> dict:
        node = self.node
        last = getattr(node, "last_frame_monotonic", None)
        return {
            "ok": True,
            "node_id": node.node_id,
            "role": getattr(node, "role", "follower"),
            "len": node.log_len,
            "hseq": node.max_hseq,
            "incarnation": getattr(node, "incarnation", 0),
            "heads": sorted([hseq, key, handle] for key, (hseq, handle)
                            in node.heads.items()),
            "leader_silence_s": (None if last is None
                                 else round(time.monotonic() - last, 6)),
        }


class ReplicaServerThread:
    """Own-loop wrapper: run a :class:`ReplicaServer` on a daemon
    thread (the conftest ``secure_alfred`` pattern) so synchronous
    hosts — tests, the follower child's main — get a listening port
    back without owning an event loop."""

    def __init__(self, node, host: str = "127.0.0.1", port: int = 0,
                 handlers: dict | None = None) -> None:
        self.server = ReplicaServer(node, host=host, port=port,
                                    handlers=handlers)
        self._loop = asyncio.new_event_loop()
        started = threading.Event()

        async def run() -> None:
            await self.server.start()
            started.set()

        self._thread = threading.Thread(
            target=lambda: (self._loop.run_until_complete(run()),
                            self._loop.run_forever()),
            daemon=True, name=f"replica-server-{node.node_id}")
        self._thread.start()
        if not started.wait(10):
            raise RuntimeError("replica server failed to start")

    @property
    def port(self) -> int:
        return self.server.port

    def close(self) -> None:
        def _shutdown() -> None:
            # Stop listening, cancel in-flight connection handlers, and
            # only THEN stop the loop — a handler parked in a read must
            # unwind (closing its writer) while the loop is still
            # alive, or teardown leaks a destroyed-pending task.
            self.server.close()
            for task in asyncio.all_tasks(self._loop):
                task.cancel()
            self._loop.call_soon(self._loop.stop)

        self._loop.call_soon_threadsafe(_shutdown)
        self._thread.join(10)


# -- client half ---------------------------------------------------------------


class NetworkReplicaLink:
    """One leader->follower edge over TCP: the ``ReplicaLink.call``
    contract (encoded frame in, decoded response header out) with
    deadlines, bounded retransmits and reconnection underneath. The
    handshake ``hello`` populates the node-shaped attributes
    (``node_id``/``log_len``/``max_hseq``/``heads``) the plane reads
    at construction, and ``self.node is self`` keeps every
    ``link.node.<attr>`` call site working unchanged."""

    def __init__(self, address, node_id: str | None = None,
                 call_timeout_s: float = DEFAULT_CALL_TIMEOUT_S,
                 retries: int = DEFAULT_RETRIES,
                 backoff_base_s: float = BACKOFF_BASE_S,
                 backoff_max_s: float = BACKOFF_MAX_S,
                 seed: int = 0) -> None:
        if isinstance(address, int):
            address = ("127.0.0.1", address)
        self.address = tuple(address)
        self.call_timeout_s = call_timeout_s
        self.retries = retries
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self._rng = random.Random(f"{seed}:{self.address}")
        self._sock: socket.socket | None = None
        self._io_lock = threading.Lock()
        # Node-shaped surface (refreshed by hello()):
        self.node_id = node_id or f"{self.address[0]}:{self.address[1]}"
        self.log_len = 0
        self.max_hseq = 0
        self.heads: dict[str, tuple[int, str]] = {}
        self.incarnation = 0
        self.role = "follower"
        self.data_dir = None  # remote: promotion needs the local path
        self.last_ok: float = 0.0
        self.stats = {"calls": 0, "retransmits": 0, "reconnects": 0,
                      "timeouts": 0}
        self._rtts: list[float] = []
        self.hello()

    #: ``plane._acked[lk.node.node_id]`` etc. — the link self-describes.
    @property
    def node(self) -> "NetworkReplicaLink":
        return self

    # -- raw round trip --------------------------------------------------------

    def _connect(self) -> socket.socket:
        sock = socket.create_connection(self.address,
                                        timeout=self.call_timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.stats["reconnects"] += 1
        return sock

    def _roundtrip(self, body: bytes) -> bytes:
        with self._io_lock:
            if self._sock is None:
                self._sock = self._connect()
            sock = self._sock
            try:
                sock.settimeout(self.call_timeout_s)
                sock.sendall(frame_body(body))
                return read_frame_raw_sync(sock)
            except Exception:
                # Whatever failed, the stream is unusable mid-frame:
                # drop it and let the retry loop redial.
                self._sock = None
                try:
                    sock.close()
                except OSError:
                    pass
                raise

    def call_raw(self, body: bytes) -> bytes:
        """Deadline + bounded retransmits with jittered exponential
        backoff. Raises :class:`ReplicationLinkDown` once the budget is
        spent — the plane's transient-failure path (count, resync on
        next contact)."""
        self.stats["calls"] += 1
        last_err: Exception | None = None
        for attempt in range(self.retries + 1):
            if attempt:
                self.stats["retransmits"] += 1
                delay = min(self.backoff_max_s,
                            self.backoff_base_s * (2 ** (attempt - 1)))
                time.sleep(delay * (0.5 + self._rng.random()))
            try:
                t0 = time.perf_counter()
                resp = self._roundtrip(body)
                rtt = time.perf_counter() - t0
                self._rtts.append(rtt)
                if len(self._rtts) > 1024:
                    del self._rtts[:512]
                self.last_ok = time.monotonic()
                return resp
            except socket.timeout as err:
                self.stats["timeouts"] += 1
                last_err = err
            except OSError as err:
                last_err = err
        raise ReplicationLinkDown(
            f"{self.node_id}: {type(last_err).__name__}: {last_err}")

    # -- the ReplicaLink contract ----------------------------------------------

    def call(self, frame: bytes) -> dict:
        from ..protocol.codec import decode_storm_body
        hdr, _payload = decode_storm_body(self.call_raw(bytes(frame)))
        return hdr

    def control(self, op: str, **kw) -> dict:
        return json.loads(
            self.call_raw(json.dumps({"op": op, **kw}).encode()))

    def hello(self) -> dict:
        d = self.control("hello")
        self.node_id = d["node_id"]
        self.log_len = d["len"]
        self.max_hseq = d["hseq"]
        self.incarnation = d.get("incarnation", 0)
        self.role = d.get("role", "follower")
        self.heads = {key: (hseq, handle)
                      for hseq, key, handle in d.get("heads", ())}
        return d

    def transport_stats(self) -> dict:
        """Aggregatable wire stats (plane gauges / monitor line)."""
        return {"rtt_s": list(self._rtts), **self.stats}

    def close(self) -> None:
        with self._io_lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


# -- fault injection -----------------------------------------------------------


class FaultyTransport:
    """Deterministic link-fault wrapper around any ``call``-shaped link
    (in-process or network). Faults are installed BY NAME per edge —
    ``install("drop", p=0.2)`` — or wholesale from a plan dict
    (``{edge: {fault: params}}``, the shape
    ``utils/faults.link_fault_plan_from_env`` parses), and healed with
    :meth:`heal`. Probabilistic faults draw from a per-edge seeded RNG,
    so a chaos scenario replays byte-identically.

    Fault semantics (all surfaced to the caller exactly as a real
    network would surface them — the plane must survive each through
    its ordinary retry/resync/dup machinery):

    * ``partition`` — every call fails, nothing delivered.
    * ``partition_send`` — requests lost: fail, nothing delivered.
    * ``partition_recv`` — responses lost: the frame IS delivered
      (follower state advances), then the call fails. The leader's
      retransmit becomes a genuine duplicate delivery.
    * ``drop`` (p) — per-call loss, nothing delivered.
    * ``delay`` (s, p) / ``slow`` (s) — added latency before delivery.
    * ``dup`` (p) — the frame delivers twice; the second (idempotent)
      response is returned.
    * ``reorder`` (p) — the frame is HELD and delivered before the
      next call instead (a genuine out-of-order arrival at the node);
      the caller sees a nack carrying the follower's current length,
      exactly what a reordering network produces, and the plane
      resyncs.
    """

    def __init__(self, inner, edge: str = "link", seed: int = 0,
                 plan: dict | None = None) -> None:
        self.inner = inner
        self.edge = edge
        self.rng = random.Random(f"{seed}:{edge}")
        self.faults: dict[str, dict] = {}
        self._held: list[bytes] = []
        self.stats = {name: 0 for name in LINK_FAULTS}
        self.stats["delivered"] = 0
        for name, params in (plan or {}).get(edge, {}).items():
            self.install(name, **params)

    #: the plane reads ``link.node.<attr>`` through the wrapper.
    @property
    def node(self):
        return self.inner.node

    def __getattr__(self, name):
        return getattr(self.inner, name)

    def install(self, name: str, **params) -> None:
        if name not in LINK_FAULTS:
            raise ValueError(f"unknown link fault {name!r} "
                             f"(known: {LINK_FAULTS})")
        self.faults[name] = params

    def heal(self, name: str | None = None) -> None:
        if name is None:
            self.faults.clear()
        else:
            self.faults.pop(name, None)

    def _chance(self, params: dict) -> bool:
        return self.rng.random() < float(params.get("p", 1.0))

    def _deliver_held(self) -> None:
        while self._held:
            try:
                self.inner.call(self._held.pop(0))
            except Exception:
                pass  # a held frame lost to a second fault stays lost

    def call(self, frame: bytes) -> dict:
        f = self.faults
        if "partition" in f:
            self.stats["partition"] += 1
            raise ReplicationLinkDown(f"{self.edge}: partition")
        if "partition_send" in f:
            self.stats["partition_send"] += 1
            raise ReplicationLinkDown(f"{self.edge}: partition (send)")
        if "slow" in f:
            self.stats["slow"] += 1
            time.sleep(float(f["slow"].get("s", 0.01)))
        if "delay" in f and self._chance(f["delay"]):
            self.stats["delay"] += 1
            time.sleep(float(f["delay"].get("s", 0.01)))
        if "drop" in f and self._chance(f["drop"]):
            self.stats["drop"] += 1
            raise ReplicationLinkDown(f"{self.edge}: dropped")
        self._deliver_held()
        if "reorder" in f and self._chance(f["reorder"]):
            # Hold this frame past the next one. The synchronous nack
            # (with the follower's REAL length, probed through the
            # link) is what a reordered arrival looks like from the
            # sender: not-yet-appended, resync me.
            self.stats["reorder"] += 1
            self._held.append(bytes(frame))
            try:
                have = self.inner.call(_frame("probe", {})).get("len", 0)
            except Exception:
                have = 0
            return {"v": 1, "k": "nack", "len": have, "reason": "reorder"}
        if "partition_recv" in f:
            self.stats["partition_recv"] += 1
            try:
                self.inner.call(frame)  # delivered; the ack is lost
            except Exception:
                pass
            raise ReplicationLinkDown(
                f"{self.edge}: partition (response lost)")
        self.stats["delivered"] += 1
        hdr = self.inner.call(frame)
        if "dup" in f and self._chance(f["dup"]):
            self.stats["dup"] += 1
            hdr = self.inner.call(frame)  # idempotent re-delivery
        return hdr


__all__ = [
    "DEFAULT_CALL_TIMEOUT_S", "DEFAULT_RETRIES", "HEARTBEAT_INTERVAL_S",
    "LEASE_S", "LINK_FAULTS", "ReplicaServer", "ReplicaServerThread",
    "NetworkReplicaLink", "FaultyTransport",
]
