"""Replication plane: quorum-shipped WAL batches, replicated head
flips, and leader failover — the jump from "crash-consistent process"
to "production cluster that loses hosts" (ROADMAP item 1).

Reference parity: the reference's ordering service is a durable,
highly-available CLUSTER — deli/scribe lambdas over Kafka, whose
partitions are themselves replicated to a follower quorum before an
offset is considered committed. Our reproduction's durability was one
host's fsync; this module adds the missing leg:

* **Log shipping** — :class:`ReplicationPlane` hooks the group-commit
  WAL's ``on_batch_durable`` seam (server/durable_store.py): every
  fsynced batch ships to F :class:`ReplicaNode` followers over the
  storm codec framing (versioned like the WAL "v" stamps), each
  follower appends the records at the SAME indices into its own
  CRC-framed replica log and fsyncs, and the plane advances a
  REPLICATED watermark once a quorum acked. The storm controller
  withholds client acks on ``min(durable, replicated)`` — an acked op
  now survives the leader's disk, not just its process.
* **Replicated head flips** — :class:`ReplicatedHeadStore` wraps a
  snapshot store and ships every ``set_head`` to the follower quorum
  BEFORE the backend flips (ship-then-flip). The ``__placement__``
  directory, storm checkpoints, cold-residency records and history
  summaries all flip through it, so a dead leader can never strand
  routing or cold state: promotion rolls the journaled flips forward.
* **Failover** — :func:`choose_promotion_candidate` picks the most
  advanced follower, :func:`promote_heads` applies its journaled head
  flips to the shared store, and a fresh storm stack built over the
  replica log (the follower lays its WAL out storm-shaped precisely
  for this) replays through the existing ``StormController.recover``
  path. The demoted ex-leader is FENCED: its plane stops shipping,
  its acks freeze at the replicated watermark, and ``_admit`` sheds
  every frame with a ``moved`` nack naming the new incarnation (the
  PR 16 ``moved_to`` machinery).

Quorum math: with F followers the leader counts itself, so a majority
of the F+1 replicas needs ``(F+1)//2`` follower acks — F=1 waits for
its only follower (2/2), F=2 for one of two (2/3). ``acks_required``
overrides it (F=2 with ``acks_required=2`` is chain-style full
replication). Head flips use the same quorum; an unreachable quorum
REFUSES the flip (checkpoint/migration fails loudly) so the backend
head can never run ahead of every follower's journal — the invariant
that makes promotion's roll-forward safe.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
from pathlib import Path

from ..native import OpLog
from ..protocol.codec import decode_storm_body, encode_storm_body
from ..utils import faults
from .durable_store import rewrite_oplog_records

#: Stream format stamp on every shipped frame ("v", exactly like the
#: storm WAL headers): a follower refuses frames newer than its reader.
REPLICATION_STREAM_VERSION = 1

#: The replica WAL lives storm-shaped inside the follower's data dir —
#: ``<dir>/spill/storm_tick_words.log`` — so promotion builds a serving
#: host DIRECTLY over the follower directory (same path the storm's own
#: spill WAL uses; see server/storm.py __init__).
REPLICA_WAL_RELPATH = os.path.join("spill", "storm_tick_words.log")

#: Journaled head flips (``[hseq, key, handle]`` records, CRC-framed).
REPLICA_HEADS_RELPATH = "replica_heads.log"

#: Journaled retention floors (one ASCII int per record): replica-side
#: WAL trim progress, durable so a restarted follower knows its replay
#: horizon without rescanning the log.
REPLICA_RETENTION_RELPATH = "replica_retention.log"

#: The follower's durable incarnation floor (one ASCII int, atomically
#: replaced): frames stamped with a LOWER incarnation are refused with
#: a ``fenced`` nack — a zombie ex-leader's ships are rejected ON THE
#: WIRE, not merely ignored, and the refusal survives a follower
#: restart.
REPLICA_INCARNATION_RELPATH = "replica_incarnation"

#: Kill classes for the chaos matrix: batch locally durable but not yet
#: shipped / shipped and quorum-acked but the leader's watermark not
#: yet advanced — recovery must prove no acked-replicated op is lost
#: whichever side of the ship the kill lands on.
REPLICATION_KILL_POINTS = ("repl.pre_ship", "repl.post_ship")

#: Records per resync batch frame (tail re-ship of a lagging follower).
RESYNC_BATCH_RECORDS = 64


class ReplicationLinkDown(OSError):
    """The follower link refused or dropped the frame (transport-level;
    the plane counts it and resyncs the follower later)."""


class ReplicationQuorumError(RuntimeError):
    """A head flip could not reach the follower quorum — the flip is
    REFUSED (backend untouched) so journals never lag the backend."""


def _frame(kind: str, header: dict, payload: bytes = b"") -> bytes:
    return encode_storm_body(
        {"v": REPLICATION_STREAM_VERSION, "k": kind, **header}, payload)


def _trimmed_filler() -> bytes:
    """The storm WAL's docs-less trimmed-tick blob — the SAME bytes
    ``server/storm.py trim_tick_blobs`` writes — so a retention-trimmed
    replica record still parses everywhere a real one would (promotion
    replay treats it as a no-op control tick, resync re-ships it
    verbatim). Imported lazily: the replica tier stays importable
    without pulling the JAX-backed storm module in."""
    from .storm import STORM_WAL_VERSION
    header = json.dumps(
        {"v": STORM_WAL_VERSION, "ts": 0, "docs": [],
         "hp": {"op": "trimmed"}}, separators=(",", ":")).encode()
    return struct.pack("<I", len(header)) + header


class ReplicaNode:
    """One follower: a storm-shaped replica WAL plus a head-flip journal
    under its own data directory. Passive — it appends what the leader
    ships, fsyncs, and acks its log length; promotion turns the
    directory into a serving host.

    Batch protocol (all frames storm-codec bodies, ``v``-stamped):

    * ``batch`` ``{seq, lens}`` + concatenated record bytes — appended
      iff ``seq`` equals the local length. A duplicate delivery
      (``seq`` below the length) acks idempotently; a gap (``seq``
      ahead) nacks with the local length so the leader re-ships the
      missing tail. Torn frames (truncated payload, bad magic) are
      rejected before any append.
    * ``head`` ``{key, handle, hseq}`` — journaled iff ``hseq`` is new
      (monotonic per plane; duplicates ack idempotently).
    * ``heads`` ``{entries: [[hseq, key, handle], ...]}`` — bulk
      journal adoption (resync of a fresh/lagging follower).
    * ``probe`` — acks the current log length (resync discovery).
    """

    def __init__(self, data_dir: str | os.PathLike,
                 node_id: str | None = None, fsync: bool = True) -> None:
        root = Path(data_dir)
        (root / "spill").mkdir(parents=True, exist_ok=True)
        self.data_dir = str(root)
        self.node_id = node_id if node_id is not None else root.name
        self.fsync = fsync
        #: "follower" (pure failover candidate) or "read-replica" (a
        #: ReadReplica — server/read_replica.py — tails this node's WAL
        #: and serves the read surface off it). Descriptive only: the
        #: batch/head/trim protocol is identical either way.
        self.role = "follower"
        #: Tail seam subscribers: ``callback(start_index, records)``
        #: after each batch append (post-fsync). See :meth:`subscribe`.
        self._subscribers: list = []
        self._wal = OpLog(root / REPLICA_WAL_RELPATH)
        self._heads_log = OpLog(root / REPLICA_HEADS_RELPATH)
        self._retention_log = OpLog(root / REPLICA_RETENTION_RELPATH)
        #: Durable incarnation floor (wire fencing): the highest "inc"
        #: stamp ever accepted; lower-stamped frames nack ``fenced``.
        self._inc_path = root / REPLICA_INCARNATION_RELPATH
        self.incarnation = 0
        try:
            self.incarnation = int(self._inc_path.read_text())
        except (FileNotFoundError, ValueError):
            pass
        #: Monotonic stamp of the last frame heard from ANY leader —
        #: the follower-side lease (``hello`` surfaces it as
        #: ``leader_silence_s``; silence past the lease makes this
        #: node promotion-eligible).
        self.last_frame_monotonic: float | None = None
        self._retained_floor = 0
        for i in range(len(self._retention_log)):
            self._retained_floor = max(
                self._retained_floor, int(self._retention_log.read(i)))
        self._lock = threading.Lock()
        #: key -> (hseq, handle): the latest journaled flip per key.
        self.heads: dict[str, tuple[int, str]] = {}
        self.max_hseq = 0
        for i in range(len(self._heads_log)):
            hseq, key, handle = json.loads(self._heads_log.read(i))
            self.heads[key] = (hseq, handle)
            self.max_hseq = max(self.max_hseq, hseq)
        self.stats = {"batches": 0, "records": 0, "dup_records": 0,
                      "gap_nacks": 0, "head_flips": 0, "rejected": 0,
                      "retained_records": 0, "fenced_frames": 0}

    @property
    def log_len(self) -> int:
        with self._lock:
            return len(self._wal)

    @property
    def retained_floor(self) -> int:
        """Indices below this are retention-trimmed (filler bytes) —
        except the leader-named live set kept alongside each floor."""
        return self._retained_floor

    def subscribe(self, callback) -> None:
        """Tail seam: ``callback(start_index, records)`` fires after a
        batch appends (post-fsync) with the fresh record bytes in WAL
        order — how a read replica learns of new ticks without polling.
        Runs on the leader's WAL writer thread, so callbacks must be
        CHEAP (note a watermark, poke a condition); heavy folding
        belongs in the subscriber's own poll loop. Exceptions are
        swallowed like the WAL's own ``on_batch_durable`` hook — a
        broken reader must never nack the leader's ship."""
        self._subscribers.append(callback)

    def on_frame(self, frame: bytes) -> bytes:
        """Handle one shipped frame; returns the encoded response frame.
        Thread-safe (the leader ships batches from the WAL writer thread
        and head flips from the serving thread)."""
        try:
            hdr, payload = decode_storm_body(frame)
        except Exception as err:  # torn/alien frame
            self.stats["rejected"] += 1
            return _frame("nack", {"len": self.log_len,
                                   "reason": f"bad-frame: {err}"})
        if hdr.get("v", 0) > REPLICATION_STREAM_VERSION:
            self.stats["rejected"] += 1
            return _frame("nack", {"len": self.log_len,
                                   "reason": "version"})
        inc = int(hdr.get("inc", 0))
        if inc < self.incarnation:
            # Zombie leader: a NEWER incarnation already shipped here.
            # The frame is REFUSED on the wire (never appended, never
            # journaled) and the nack names the floor — the stale
            # plane's triage demotes itself on sight of it.
            self.stats["fenced_frames"] += 1
            return _frame("nack", {"len": self.log_len,
                                   "reason": "fenced",
                                   "inc": self.incarnation})
        if inc > self.incarnation:
            self._adopt_incarnation(inc)
        self.last_frame_monotonic = time.monotonic()
        kind = hdr.get("k")
        if kind == "batch":
            return self._on_batch(hdr, payload)
        if kind == "head":
            return self._on_head(hdr["hseq"], hdr["key"], hdr["handle"])
        if kind == "heads":
            with self._lock:
                for hseq, key, handle in hdr["entries"]:
                    self._journal_head(hseq, key, handle)
                if self.fsync:
                    self._heads_log.sync()
            return _frame("ack", {"len": self.log_len,
                                  "hseq": self.max_hseq})
        if kind == "probe":
            return _frame("ack", {"len": self.log_len,
                                  "hseq": self.max_hseq})
        if kind == "trim":
            return self._on_trim(hdr["floor"], hdr.get("keep"))
        self.stats["rejected"] += 1
        return _frame("nack", {"len": self.log_len, "reason": "kind"})

    def _on_batch(self, hdr: dict, payload) -> bytes:
        seq, lens = hdr["seq"], hdr["lens"]
        if sum(lens) != len(payload):
            # Torn mid-payload: the frame claims more record bytes than
            # arrived — reject whole (a partial append would CRC-frame
            # garbage at a real index and poison later reads).
            self.stats["rejected"] += 1
            return _frame("nack", {"len": self.log_len,
                                   "reason": "torn-payload"})
        fresh_start = 0
        fresh: list[bytes] = []
        with self._lock:
            have = len(self._wal)
            if seq > have:
                # Reordered/lost predecessor: refuse the gap, tell the
                # leader where the tail starts.
                self.stats["gap_nacks"] += 1
                return _frame("nack", {"len": have, "reason": "gap"})
            off = 0
            for i, ln in enumerate(lens):
                rec = bytes(payload[off:off + ln])
                off += ln
                if seq + i < have:
                    self.stats["dup_records"] += 1
                    continue  # duplicate delivery: already journaled
                got = self._wal.append(rec)
                assert got == seq + i, (got, seq + i)
                have = got + 1
                if not fresh:
                    fresh_start = got
                fresh.append(rec)
                self.stats["records"] += 1
            if fresh and self.fsync:
                self._wal.sync()
            self.stats["batches"] += 1
        if fresh:
            # Outside the lock: a subscriber may read back through the
            # node (read()/log_len take it).
            for cb in list(self._subscribers):
                try:
                    cb(fresh_start, fresh)
                except Exception:
                    pass
        return _frame("ack", {"len": have})

    def _on_head(self, hseq: int, key: str, handle: str) -> bytes:
        with self._lock:
            if self._journal_head(hseq, key, handle) and self.fsync:
                self._heads_log.sync()
            else:
                self.stats["dup_records"] += 1
        return _frame("ack", {"len": self.log_len, "hseq": self.max_hseq})

    def _journal_head(self, hseq: int, key: str, handle: str) -> bool:
        if hseq <= self.max_hseq:
            return False  # duplicate/old flip: idempotent
        self._heads_log.append(
            json.dumps([hseq, key, handle]).encode())
        self.heads[key] = (hseq, handle)
        self.max_hseq = hseq
        self.stats["head_flips"] += 1
        return True

    def _adopt_incarnation(self, inc: int) -> None:
        """Raise the durable fencing floor (atomic replace + fsync):
        once adopted, every lower-stamped frame is refused forever —
        including across this follower's own restarts."""
        with self._lock:
            if inc <= self.incarnation:
                return
            tmp = self._inc_path.with_name(self._inc_path.name + ".tmp")
            with open(tmp, "w") as fh:
                fh.write(str(int(inc)))
                fh.flush()
                if self.fsync:
                    os.fsync(fh.fileno())
            os.replace(tmp, self._inc_path)
            self.incarnation = int(inc)

    def _on_trim(self, floor: int, keep=None) -> bytes:
        try:
            trimmed = self.retain(floor, keep)
        except Exception as err:
            self.stats["rejected"] += 1
            return _frame("nack", {"len": self.log_len,
                                   "reason": f"trim: {err}"})
        return _frame("ack", {"len": self.log_len, "trimmed": trimmed})

    def retain(self, floor: int, keep=None) -> int:
        """Replica-side WAL retention (the PR 19 residue): shrink every
        record below ``floor`` — except the leader-named ``keep`` set,
        the ticks the leader itself still holds live (catch-up-indexed,
        control, history-pinned) — to the storm trimmed-tick filler.
        Record COUNT and indices are preserved, so the nack-driven
        gap/dup stream recovery and a later promotion replay are
        untouched, and the follower's bytes converge on exactly what
        the leader's own history trim left behind. The floor journals
        FIRST (fsynced) when it advances; the rewrite itself publishes
        atomically (tmp + rename), so a kill mid-trim keeps the
        original log and the next shipped floor reapplies. Returns the
        number of records shrunk."""
        keep = frozenset(keep or ())
        with self._lock:
            floor = min(int(floor), len(self._wal))
            filler = _trimmed_filler()
            victims = [i for i in range(floor)
                       if i not in keep
                       and len(self._wal.read(i)) > len(filler)]
            if floor > self._retained_floor:
                self._retention_log.append(str(floor).encode())
                if self.fsync:
                    self._retention_log.sync()
                self._retained_floor = floor
            if not victims:
                return 0
            hit = set(victims)

            def transform(idx: int, data: bytes) -> bytes | None:
                return filler if idx in hit else None

            self._wal, changed = rewrite_oplog_records(
                self._wal, Path(self.data_dir) / REPLICA_WAL_RELPATH,
                transform)
            self.stats["retained_records"] += changed
            return changed

    def read(self, index: int) -> bytes:
        with self._lock:
            return self._wal.read(index)

    def close(self) -> None:
        with self._lock:
            self._wal.close()
            self._heads_log.close()
            self._retention_log.close()


class ReplicaLink:
    """In-process transport carrying ENCODED frames to one follower —
    the seam a networked deployment replaces with the bridge transport.
    Tests flip :attr:`down` (partition) or set :attr:`transform`
    (byte-level corruption/truncation) to exercise the stream's failure
    modes; ``faults.install_failure("repl.ship")`` injects transient
    send failures without touching the link object."""

    def __init__(self, node: ReplicaNode) -> None:
        self.node = node
        self.down = False
        self.transform = None  # bytes -> bytes | None (None = dropped)

    def call(self, frame: bytes) -> dict:
        if self.down:
            raise ReplicationLinkDown(self.node.node_id)
        faults.failpoint("repl.ship")
        if self.transform is not None:
            frame = self.transform(frame)
            if frame is None:
                raise ReplicationLinkDown(self.node.node_id)
        hdr, _payload = decode_storm_body(self.node.on_frame(bytes(frame)))
        return hdr


class ReplicationPlane:
    """Leader-side quorum shipper. Attach to a storm controller with
    :meth:`attach`: the WAL's ``on_batch_durable`` hook then ships every
    fsynced batch SYNCHRONOUSLY on the writer thread (before the durable
    watermark advances), so ``wal.sync()`` returning already implies the
    ship attempt completed — the pipelined tick hides the whole round
    trip behind device dispatch exactly as it hides the fsync. Acks
    gate on :attr:`replicated_len` via the storm's effective watermark;
    a partitioned quorum freezes it and the controller withholds acks
    (clients resend — the degraded-WAL discipline, one tier out)."""

    def __init__(self, nodes, acks_required: int | None = None,
                 label: str = "leader") -> None:
        # Anything ``call``-shaped is already a link (in-process
        # ReplicaLink, a NetworkReplicaLink, a FaultyTransport wrapper);
        # bare nodes get the in-process link.
        links = [n if hasattr(n, "call") else ReplicaLink(n)
                 for n in nodes]
        if not links:
            raise ValueError("a replication plane needs >= 1 follower")
        self.links = links
        f = len(links)
        self.acks_required = ((f + 1) // 2 if acks_required is None
                              else max(1, min(acks_required, f)))
        self.label = label
        self.role = "leader"
        self.moved_to: str | None = None
        #: Wire-fencing stamp: every shipped frame carries it, and a
        #: follower whose durable floor is higher refuses the frame
        #: (``fenced`` nack) — promotion bumps it past every journal.
        self.incarnation = max(
            (getattr(lk.node, "incarnation", 0) for lk in links),
            default=0)
        # Failure detection (lease-based, armed by
        # start_failure_detector; without it quorum_ok only tracks
        # follower-set size — the in-process legacy behavior).
        self.lease_s: float | None = None
        self.hb_interval_s: float = 0.0
        #: How long writes PARK (admitted, buffered, unacked) under a
        #: lost quorum before _admit sheds them with a retry hint.
        self.park_max_s: float = 5.0
        self._hb_thread = None
        self._hb_stop: threading.Event | None = None
        self._degraded_since: float | None = None
        now = time.monotonic()
        self._last_ok = {lk.node.node_id: now for lk in links}
        self._lock = threading.Lock()
        self._acked = {lk.node.node_id: lk.node.log_len for lk in links}
        self._replicated = 0
        # Monotonic head-flip stamp, seeded PAST every journal so a
        # promoted incarnation's fresh plane never stamps below flips
        # the old leader already shipped.
        self._hseq = max((lk.node.max_hseq for lk in links), default=0)
        self._heads: dict[str, tuple[int, str]] = {}
        for lk in links:
            for key, (hseq, handle) in lk.node.heads.items():
                if hseq > self._heads.get(key, (0, ""))[0]:
                    self._heads[key] = (hseq, handle)
        self.storm = None
        self._wal = None
        self._metrics = None
        self.stats = {"batches_shipped": 0, "ship_failures": 0,
                      "resyncs": 0, "head_flips_shipped": 0,
                      "quorum_refusals": 0, "retention_floors_shipped": 0,
                      "ship_retries": 0, "heartbeat_misses": 0,
                      "fenced_nacks": 0, "followers_dropped": 0}

    def _stamp(self, kind: str, header: dict, payload: bytes = b"") \
            -> bytes:
        """A plane frame with this incarnation's fencing stamp."""
        if self.incarnation:
            header = {"inc": self.incarnation, **header}
        return _frame(kind, header, payload)

    # -- wiring ----------------------------------------------------------------

    def attach(self, storm) -> "ReplicationPlane":
        """Wire into a serving controller: resync every follower to the
        current durable length (a reopened leader may hold history the
        followers missed), then hook the shipping seam and the ack
        gate. Idempotent per storm."""
        assert storm._group_wal is not None, \
            "replication needs durability='group' (the WAL is the log)"
        self.storm = storm
        self._wal = storm._group_wal
        self._metrics = storm.merge_host.metrics
        durable = self._wal.durable_len
        for link in self.links:
            self._resync(link, upto=durable)
        self._advance()
        self._wal.on_batch_durable = self._ship_batch
        storm.replication = self
        self._update_gauges()
        return self

    @property
    def fenced(self) -> bool:
        return self.role == "demoted"

    def fence(self, moved_to: str | None = None) -> None:
        """Demote this leader (a newer incarnation serves): shipping
        stops, the replicated watermark freezes (withheld acks stay
        withheld forever — the zombie never acks again), and ``_admit``
        sheds every frame with a ``moved`` nack naming ``moved_to``."""
        self.role = "demoted"
        self.moved_to = moved_to
        self._update_gauges()

    @property
    def replicated_len(self) -> int:
        """Records a follower quorum has journaled+fsynced: the
        acked-replicated watermark the storm gates client acks on."""
        with self._lock:
            return self._replicated

    @property
    def follower_lag(self) -> int:
        """Leader durable length minus the slowest follower's acked
        length — the resync debt a failover would have to absorb if the
        most advanced follower also died."""
        durable = self._wal.durable_len if self._wal is not None else 0
        with self._lock:
            slowest = min(self._acked.values(), default=0)
        return max(0, durable - slowest)

    # -- shipping (WAL writer thread) ------------------------------------------

    def _ship_batch(self, records: list) -> None:
        if self.fenced or not records:
            return
        faults.crashpoint("repl.pre_ship")
        seq = records[0][0]
        frame = self._stamp(
            "batch", {"seq": seq, "lens": [len(b) for _i, b in records]},
            b"".join(b for _i, b in records))
        end = records[-1][0] + 1
        for link in self.links:
            self._ship_to(link, frame, end)
        self._advance()
        self.stats["batches_shipped"] += 1
        self._update_gauges()
        faults.crashpoint("repl.post_ship")

    def _ship_to(self, link: ReplicaLink, frame: bytes, end: int) -> None:
        """Ship one frame to one follower, triaging the failure modes:

        * TRANSIENT (timeout/reset/partition — ``ReplicationLinkDown``
          or any other ``OSError``): count it, retry ONCE immediately
          (the frame is idempotent — a dup delivery acks), and leave
          the follower's acked watermark alone; the next contact
          (heartbeat or batch) resyncs the missing tail.
        * PERMANENT — ``fenced`` nack: a newer incarnation owns this
          quorum, so THIS plane is the zombie — demote self, stop
          shipping. ``version`` nack: the follower cannot read this
          stream format, ever — drop it from the plane (quorum math
          shrinks with it; an unreachable quorum parks writes).
        * Gap nack: the ordinary behind-follower path — re-ship its
          missing tail from the leader log (resync's upper bound
          retries the batch implicitly).
        """
        hdr = None
        for attempt in (0, 1):
            try:
                hdr = link.call(frame)
                break
            except ReplicationLinkDown:
                self.stats["ship_failures"] += 1
                if attempt:
                    return
                self.stats["ship_retries"] += 1
            except Exception:
                self.stats["ship_failures"] += 1
                return
        if hdr is None:
            return
        if hdr.get("k") == "nack":
            reason = hdr.get("reason")
            if reason == "fenced":
                self.stats["fenced_nacks"] += 1
                self.fence(moved_to=self.moved_to)
                return
            if reason == "version":
                self._drop_follower(link, reason="version")
                return
            self._resync(link, upto=end)
            return
        nid = link.node.node_id
        self._last_ok[nid] = time.monotonic()
        with self._lock:
            self._acked[nid] = max(self._acked[nid], hdr["len"])

    def _drop_follower(self, link, reason: str) -> None:
        """Remove a PERMANENTLY incompatible follower from the plane.
        ``acks_required`` is unchanged — losing a follower must never
        silently weaken the quorum; if the remainder cannot reach it,
        writes park and head flips refuse, loudly."""
        with self._lock:
            if link in self.links:
                self.links.remove(link)
            self._acked.pop(link.node.node_id, None)
        self._last_ok.pop(link.node.node_id, None)
        self.stats["followers_dropped"] += 1
        close = getattr(link, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass
        self._update_gauges()

    def _resync(self, link: ReplicaLink, upto: int | None = None) -> None:
        """Bring one follower to ``upto`` (default: leader durable):
        probe its length, re-ship the tail in bounded batches straight
        from the leader log — records the history plane already trimmed
        arrive as the SAME filler bytes the leader holds, so a follower
        whose lag exceeded the retention floor converges on snapshot
        (journaled heads) + log tail exactly like a local recovery —
        then bulk-ship the journaled head flips it missed."""
        if self._wal is None:
            return
        if upto is None:
            upto = self._wal.durable_len
        self.stats["resyncs"] += 1
        try:
            have = link.call(self._stamp("probe", {}))["len"]
            while have < upto:
                batch = range(have, min(upto, have + RESYNC_BATCH_RECORDS))
                recs = [self._wal.read(i) for i in batch]
                hdr = link.call(self._stamp(
                    "batch",
                    {"seq": batch.start, "lens": [len(r) for r in recs]},
                    b"".join(recs)))
                if hdr.get("k") != "ack":
                    self.stats["ship_failures"] += 1
                    if hdr.get("reason") == "fenced":
                        self.stats["fenced_nacks"] += 1
                        self.fence(moved_to=self.moved_to)
                    return
                have = hdr["len"]
            with self._lock:
                entries = sorted(
                    [hseq, key, handle]
                    for key, (hseq, handle) in self._heads.items())
            if entries:
                link.call(self._stamp("heads", {"entries": entries}))
            self._last_ok[link.node.node_id] = time.monotonic()
            with self._lock:
                self._acked[link.node.node_id] = max(
                    self._acked[link.node.node_id], have)
        except Exception:
            self.stats["ship_failures"] += 1

    def _advance(self) -> None:
        with self._lock:
            acked = sorted(self._acked.values(), reverse=True)
            if len(acked) < self.acks_required:
                return  # dropped below quorum size: watermark freezes
            quorum = acked[self.acks_required - 1]
            self._replicated = max(self._replicated, quorum)

    # -- failure detection (lease-based heartbeats) ----------------------------

    @property
    def quorum_ok(self) -> bool:
        """``acks_required`` followers hold a FRESH lease. Without an
        armed detector (``lease_s`` unset) only the follower-set size
        counts — the in-process legacy semantics, where a slow link
        merely withholds acks."""
        if len(self.links) < self.acks_required:
            return False
        if self.lease_s is None:
            return True
        now = time.monotonic()
        live = sum(1 for lk in self.links
                   if now - self._last_ok.get(lk.node.node_id, 0.0)
                   <= self.lease_s)
        return live >= self.acks_required

    def quorum_degraded_s(self) -> float | None:
        """Seconds the quorum has been lost (None while healthy) —
        the storm's park-then-shed clock."""
        if self.quorum_ok:
            self._degraded_since = None
            return None
        now = time.monotonic()
        if self._degraded_since is None:
            self._degraded_since = now
        return now - self._degraded_since

    def heartbeat(self) -> bool:
        """One failure-detector round: probe links idle past the
        heartbeat interval, renew leases on success, and — the heal
        path — resync any follower whose acked length fell behind the
        durable frontier, so parked writes drain as soon as the first
        probe lands instead of waiting for the next batch. Returns
        ``quorum_ok``."""
        if self.fenced:
            return False
        now = time.monotonic()
        durable = self._wal.durable_len if self._wal is not None else None
        for link in list(self.links):
            nid = link.node.node_id
            if self.hb_interval_s \
                    and now - self._last_ok.get(nid, 0.0) \
                    < self.hb_interval_s:
                continue  # recent traffic IS the heartbeat
            try:
                hdr = link.call(self._stamp("probe", {}))
            except Exception:
                self.stats["heartbeat_misses"] += 1
                continue
            if hdr.get("k") != "ack":
                if hdr.get("reason") == "fenced":
                    self.stats["fenced_nacks"] += 1
                    self.fence(moved_to=self.moved_to)
                    return False
                self.stats["heartbeat_misses"] += 1
                continue
            self._last_ok[nid] = time.monotonic()
            with self._lock:
                self._acked[nid] = max(self._acked[nid], hdr["len"])
            if durable is not None and hdr["len"] < durable:
                self._resync(link)
        self._advance()
        ok = self.quorum_ok
        self._update_gauges()
        return ok

    def start_failure_detector(self, interval_s: float = 0.5,
                               lease_s: float = 2.0,
                               park_max_s: float | None = None) -> None:
        """Arm lease-based failure detection: a daemon thread probes
        every ``interval_s``; a follower silent past ``lease_s`` stops
        counting toward the quorum, and a lost quorum parks writes
        (``park_max_s`` caps the park before _admit sheds)."""
        self.hb_interval_s = float(interval_s)
        self.lease_s = float(lease_s)
        if park_max_s is not None:
            self.park_max_s = float(park_max_s)
        if self._hb_thread is not None:
            return
        self._hb_stop = threading.Event()

        def loop() -> None:
            while not self._hb_stop.wait(self.hb_interval_s):
                try:
                    self.heartbeat()
                except Exception:
                    pass  # the detector must outlive any one bad round

        self._hb_thread = threading.Thread(
            target=loop, daemon=True,
            name=f"repl-heartbeat-{self.label}")
        self._hb_thread.start()

    def stop_failure_detector(self) -> None:
        if self._hb_stop is not None:
            self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(5)
            self._hb_thread = None
            self._hb_stop = None

    # -- retention (checkpoint path) -------------------------------------------

    def _live_below(self, floor: int) -> list[int]:
        """WAL indices below ``floor`` the leader still holds LIVE —
        anything that isn't already a trimmed/padding filler: real doc
        batches the catch-up index may serve, history/mega control
        ticks, pinned ranges. Followers must keep exactly these so
        their read surface stays byte-identical to the leader's."""
        keep = []
        for i in range(floor):
            data = bytes(self._wal.read(i))
            try:
                hlen = struct.unpack_from("<I", data)[0]
                hdr = json.loads(data[4:4 + hlen])
            except Exception:
                keep.append(i)  # unparseable: never discard blindly
                continue
            hp = hdr.get("hp")
            if (hdr.get("docs") or hdr.get("mg") is not None
                    or (hp is not None and hp.get("op") != "trimmed")):
                keep.append(i)
        return keep

    def ship_retention(self, floor: int) -> None:
        """Replica-side WAL retention: after a checkpoint publishes,
        ship the snapshot tick watermark as the followers' trim floor
        plus the sub-floor indices the leader itself still holds live.
        Followers shrink everything else to the trimmed filler (see
        :meth:`ReplicaNode.retain`) — follower disks now track the
        leader's own trim instead of growing unbounded. Best-effort,
        no quorum: retention is hygiene, and a follower that misses a
        trim just holds bytes until the next one (or until resync
        re-ships the leader's fillers verbatim)."""
        if self.fenced or self._wal is None or floor <= 0:
            return
        frame = self._stamp("trim", {"floor": int(floor),
                                     "keep": self._live_below(int(floor))})
        for link in self.links:
            try:
                link.call(frame)
            except Exception:
                self.stats["ship_failures"] += 1
        self.stats["retention_floors_shipped"] += 1

    # -- head flips (serving thread) -------------------------------------------

    def ship_head(self, key: str, handle: str) -> None:
        """Journal one head flip on the follower quorum BEFORE the
        caller flips the backend. Raises ReplicationQuorumError (flip
        refused, backend untouched) when fewer than ``acks_required``
        followers journaled it — the invariant promotion relies on:
        every backend head is present in >= quorum journals."""
        if self.fenced:
            raise ReplicationQuorumError(
                f"head flip on a demoted leader (promoted incarnation: "
                f"{self.moved_to!r})")
        with self._lock:
            self._hseq += 1
            hseq = self._hseq
            self._heads[key] = (hseq, handle)
        frame = self._stamp("head", {"hseq": hseq, "key": key,
                                     "handle": handle})
        acks = 0
        for link in list(self.links):
            try:
                hdr = link.call(frame)
            except Exception:
                self.stats["ship_failures"] += 1
                continue
            if hdr.get("k") == "ack":
                acks += 1
                self._last_ok[link.node.node_id] = time.monotonic()
            elif hdr.get("reason") == "fenced":
                self.stats["fenced_nacks"] += 1
                self.fence(moved_to=self.moved_to)
                raise ReplicationQuorumError(
                    f"head flip for {key!r} fenced by a newer "
                    f"incarnation; this leader is demoted")
            elif hdr.get("reason") == "version":
                self._drop_follower(link, reason="version")
        if acks < self.acks_required:
            self.stats["quorum_refusals"] += 1
            raise ReplicationQuorumError(
                f"head flip for {key!r} reached {acks}/"
                f"{self.acks_required} followers; flip refused")
        self.stats["head_flips_shipped"] += 1

    # -- observability ---------------------------------------------------------

    def _update_gauges(self) -> None:
        m = self._metrics
        if m is None:
            return
        durable = self._wal.durable_len if self._wal is not None else 0
        m.gauge("repl.role_code").set(
            {"leader": 1, "follower": 2, "demoted": 3}.get(self.role, 0))
        m.gauge("repl.followers").set(len(self.links))
        m.gauge("repl.lag").set(self.follower_lag)
        m.gauge("repl.watermark_gap").set(
            max(0, durable - self.replicated_len))
        m.gauge("repl.shipped_batches").set(
            self.stats["batches_shipped"])
        m.gauge("repl.quorum_ok").set(1 if self.quorum_ok else 0)
        deg = self.quorum_degraded_s()
        m.gauge("repl.degraded_s").set(
            0.0 if deg is None else round(deg, 3))
        parked = 0
        if deg is not None and self.storm is not None:
            parked = self.storm._pending_docs
        m.gauge("repl.parked_docs").set(parked)
        # Wire-level stats exist only on networked links; aggregate
        # across edges so the monitor gets one transport line.
        rtts: list = []
        agg = {"calls": 0, "retransmits": 0, "reconnects": 0,
               "timeouts": 0}
        netlinks = 0
        for lk in self.links:
            ts = getattr(lk, "transport_stats", None)
            if ts is None:
                continue
            netlinks += 1
            s = ts()
            rtts.extend(s.get("rtt_s", ()))
            for k in agg:
                agg[k] += s.get(k, 0)
        if netlinks or self.lease_s is not None:
            rtts.sort()

            def pct(q: float) -> float:
                if not rtts:
                    return 0.0
                return rtts[min(len(rtts) - 1,
                                int(q * (len(rtts) - 1)))]

            m.gauge("transport.links").set(netlinks)
            m.gauge("transport.rtt_p50_ms").set(
                round(1000 * pct(0.50), 3))
            m.gauge("transport.rtt_p99_ms").set(
                round(1000 * pct(0.99), 3))
            m.gauge("transport.calls").set(agg["calls"])
            m.gauge("transport.retransmits").set(agg["retransmits"])
            m.gauge("transport.reconnects").set(agg["reconnects"])
            m.gauge("transport.timeouts").set(agg["timeouts"])
            m.gauge("transport.heartbeat_misses").set(
                self.stats["heartbeat_misses"])
            open_partitions = 0
            if self.lease_s is not None:
                now = time.monotonic()
                open_partitions = sum(
                    1 for lk in self.links
                    if now - self._last_ok.get(lk.node.node_id, 0.0)
                    > self.lease_s)
            m.gauge("transport.open_partitions").set(open_partitions)


class ReplicatedHeadStore:
    """Snapshot-store wrapper (the historian pattern) that puts every
    ``set_head`` on the replication plane: ship-then-flip. Uploads,
    reads and releases pass straight through — chunk content is
    content-addressed and idempotent; only the head REF decides what a
    recovery sees, so only the ref rides the quorum."""

    def __init__(self, backend, plane: ReplicationPlane) -> None:
        self._backend = backend
        self._plane = plane

    def set_head(self, doc_id: str, handle: str) -> None:
        self._plane.ship_head(doc_id, handle)
        self._backend.set_head(doc_id, handle)

    def upload(self, doc_id: str, snapshot, put_object=None):
        if put_object is not None:
            return self._backend.upload(doc_id, snapshot,
                                        put_object=put_object)
        return self._backend.upload(doc_id, snapshot)

    def get(self, doc_id: str, handle=None, *args, **kwargs):
        return self._backend.get(doc_id, handle, *args, **kwargs)

    def head(self, doc_id: str):
        return self._backend.head(doc_id)

    def release(self, doc_id: str, handle: str):
        return self._backend.release(doc_id, handle)

    def __getattr__(self, name):
        return getattr(self._backend, name)


# -- failover -----------------------------------------------------------------


def choose_promotion_candidate(nodes: list[ReplicaNode]) -> ReplicaNode:
    """The follower to promote: longest replica log first (it holds
    every record any quorum could have acked — zero acked-replicated
    ops lost), freshest head journal second, node id as the
    deterministic tiebreak."""
    return max(nodes,
               key=lambda n: (n.log_len, n.max_hseq, n.node_id))


def promote_heads(nodes: list[ReplicaNode], store) -> int:
    """Roll the journaled head flips forward onto the shared store:
    merge every surviving follower's journal (highest ``hseq`` per key
    wins) and flip each backend head that differs. Safe by the quorum
    invariant — a backend head was only ever flipped AFTER >= quorum
    followers journaled it, so with a surviving quorum the merged
    journal can never be older than the backend; flips the dead leader
    shipped but never applied (the crash window between ship and flip)
    roll FORWARD here. Returns the number of heads flipped."""
    merged: dict[str, tuple[int, str]] = {}
    for node in nodes:
        for key, (hseq, handle) in node.heads.items():
            if hseq > merged.get(key, (0, ""))[0]:
                merged[key] = (hseq, handle)
    flipped = 0
    for key, (_hseq, handle) in sorted(merged.items()):
        if store.head(key) != handle:
            store.set_head(key, handle)
            flipped += 1
    return flipped


def promote(label: str, nodes: list[ReplicaNode], shared_snapshots,
            cluster=None, num_docs: int = 64,
            follower_dirs: list[str] | None = None,
            acks_required: int | None = None, **storm_kw) -> tuple:
    """Full failover: pick the most advanced follower, roll its
    journaled heads onto the shared store, build a fresh serving host
    OVER the follower's directory (its replica WAL is storm-shaped —
    same spill path, same record indices), recover through the normal
    snapshot + WAL-tail path, and re-arm replication toward the
    remaining followers (plus any fresh ``follower_dirs``, resynced
    from zero through the plane's own tail re-ship). With a
    ``cluster``, the new host replaces the dead label and the
    directory's incarnation stamp bumps — the PR 16 ``moved_to``
    machinery then routes shed clients of the old incarnation here.

    Returns ``(storm, plane, report)`` where the report carries the
    promotion blackout in ms (dead leader detected -> new leader
    serving) and what was rolled forward."""
    from ..parallel.placement import make_cluster_host

    t0 = time.perf_counter()
    candidate = choose_promotion_candidate(nodes)
    flipped = promote_heads(nodes, shared_snapshots)
    remaining = [n for n in nodes if n is not candidate]
    followers = list(remaining)
    for d in follower_dirs or []:
        followers.append(ReplicaNode(d))
    plane = ReplicationPlane(followers, acks_required=acks_required,
                             label=label)
    # Fence the dead incarnation ON THE WIRE: bump past every journal's
    # durable floor before the first stamped frame ships (attach
    # resyncs), so the quorum refuses the zombie's frames outright.
    plane.incarnation = 1 + max(
        (getattr(n, "incarnation", 0) for n in nodes), default=0)
    store = ReplicatedHeadStore(shared_snapshots, plane)
    candidate.close()  # the promoted storm owns the WAL file now
    storm = make_cluster_host(label, candidate.data_dir, store,
                              num_docs=num_docs, **storm_kw)
    info = storm.recover()
    plane.attach(storm)
    blackout_ms = 1000.0 * (time.perf_counter() - t0)
    if cluster is not None:
        cluster.fail_over(label, storm, blackout_ms=blackout_ms)
    if plane._metrics is not None:
        plane._metrics.gauge("repl.last_failover_blackout_ms").set(
            round(blackout_ms, 3))
    report = {"promoted_node": candidate.node_id,
              "log_len": len(storm._blob_log),
              "heads_rolled_forward": flipped,
              "replayed_ticks": info["replayed_ticks"],
              "blackout_ms": round(blackout_ms, 3)}
    return storm, plane, report


def make_replicated_host(label: str, data_dir: str, shared_snapshots,
                         follower_dirs: list[str],
                         acks_required: int | None = None,
                         num_docs: int = 64, **storm_kw) -> tuple:
    """One replicated serving host: a cluster host whose snapshot-store
    head flips and WAL batches both ride a fresh plane over
    ``follower_dirs``. Returns ``(storm, plane)``."""
    from ..parallel.placement import make_cluster_host

    # A follower may be a bare directory (in-process node) or anything
    # ``call``-shaped — a NetworkReplicaLink to another OS process, or
    # a FaultyTransport wrapping either.
    nodes = [d if hasattr(d, "call") else ReplicaNode(d)
             for d in follower_dirs]
    plane = ReplicationPlane(nodes, acks_required=acks_required,
                             label=label)
    store = ReplicatedHeadStore(shared_snapshots, plane)
    storm = make_cluster_host(label, data_dir, store,
                              num_docs=num_docs, **storm_kw)
    plane.attach(storm)
    return storm, plane


__all__ = [
    "REPLICATION_STREAM_VERSION", "REPLICATION_KILL_POINTS",
    "REPLICA_WAL_RELPATH", "REPLICA_RETENTION_RELPATH",
    "REPLICA_INCARNATION_RELPATH",
    "ReplicaNode", "ReplicaLink", "ReplicationPlane",
    "ReplicatedHeadStore", "ReplicationLinkDown",
    "ReplicationQuorumError", "choose_promotion_candidate",
    "promote_heads", "promote", "make_replicated_host",
]
