"""In-process partitioned message bus — the ordering transport seam.

Reference parity: the Kafka layer of server/routerlicious
(services-ordering-*: topics ``rawdeltas``/``deltas``, partitioned by
document, consumer groups with committed offsets —
routerlicious/config/config.json:26-38). This object model is the seam a
native transport implements: partition-FIFO ordered, durable append-only
logs, at-least-once delivery with consumer-committed offsets (replay from
the last commit after a crash — kafka-service/checkpointManager.ts:24).
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Any


@dataclass(frozen=True, slots=True)
class BusMessage:
    offset: int
    key: str
    value: Any


def partition_for(key: str, num_partitions: int) -> int:
    """Stable partitioner (crc32, not Python's randomized hash)."""
    return zlib.crc32(key.encode()) % num_partitions


@dataclass
class _Partition:
    log: list[BusMessage] = field(default_factory=list)
    #: Offset of ``log[0]`` — retention trims the in-memory prefix below
    #: the slowest registered group's commit; offsets stay stable.
    base: int = 0

    def append(self, key: str, value: Any) -> int:
        offset = self.base + len(self.log)
        self.log.append(BusMessage(offset, key, value))
        return offset

    def trim(self, upto: int) -> int:
        """Drop messages below offset ``upto`` from memory (they remain
        in any durable backend's on-disk log). Returns messages freed."""
        cut = min(max(0, upto - self.base), len(self.log))
        if cut:
            del self.log[:cut]
            self.base += cut
        return cut


class Topic:
    def __init__(self, name: str, num_partitions: int) -> None:
        self.name = name
        self.partitions = [_Partition() for _ in range(num_partitions)]

    @property
    def num_partitions(self) -> int:
        return len(self.partitions)

    def produce(self, key: str, value: Any) -> tuple[int, int]:
        """Append; returns (partition, offset). Per-key FIFO holds because a
        key always maps to the same partition."""
        pid = partition_for(key, self.num_partitions)
        return pid, self.partitions[pid].append(key, value)

    def read(self, partition: int, from_offset: int,
             max_messages: int | None = None) -> list[BusMessage]:
        part = self.partitions[partition]
        start = from_offset - part.base
        if start < 0:
            # A REAL error, not an assert (python -O must not turn this
            # into silently serving the newest messages misattributed to
            # trimmed offsets): the group attached after retention
            # passed its position — register every group before
            # enabling a horizon.
            raise LookupError(
                f"{self.name}/{partition}: read from offset "
                f"{from_offset} below the retention base {part.base} — "
                "a consumer group must register before the horizon "
                "passes its position")
        out = part.log[start:]
        return out if max_messages is None else out[:max_messages]


class MessageBus:
    """Topics + durable consumer-group offsets.

    ``retention_messages`` (opt-in) bounds each partition's IN-MEMORY
    log: once every registered consumer group has committed past a
    message AND the partition holds more than the horizon, the consumed
    prefix is trimmed (the Kafka ``log.retention`` analog — the service
    tier's message history stops scaling with total history; BENCH_r12's
    residual cold-doc RAM slope lived exactly here). Nothing uncommitted
    is ever trimmed: one lagging group pins the log, exactly like a slow
    Kafka consumer pins its segment."""

    def __init__(self, retention_messages: int | None = None) -> None:
        self._topics: dict[str, Topic] = {}
        # (topic, group, partition) -> next offset to read
        self._offsets: dict[tuple[str, str, int], int] = {}
        self.retention_messages = retention_messages
        # Groups that ever attached a Consumer, per topic: the retention
        # floor is the MIN committed offset across them (a group that
        # registered but never committed pins at 0 — safe by default).
        self._groups: dict[str, set[str]] = {}

    def create_topic(self, name: str, num_partitions: int = 4) -> Topic:
        if name not in self._topics:
            self._topics[name] = Topic(name, num_partitions)
        return self._topics[name]

    def topic(self, name: str) -> Topic:
        return self._topics[name]

    def register_group(self, topic: str, group: str) -> None:
        """Record a consumer group against the retention floor (Consumer
        does this on attach)."""
        self._groups.setdefault(topic, set()).add(group)

    def produce(self, topic: str, key: str, value: Any) -> tuple[int, int]:
        return self._topics[topic].produce(key, value)

    # -- consumer-group offsets (commit = checkpoint) -------------------------

    def committed(self, topic: str, group: str, partition: int) -> int:
        return self._offsets.get((topic, group, partition), 0)

    def commit(self, topic: str, group: str, partition: int,
               next_offset: int) -> None:
        self._offsets[(topic, group, partition)] = next_offset
        if self.retention_messages is not None:
            self._maybe_trim(topic, partition)

    def _maybe_trim(self, topic: str, partition: int) -> None:
        t = self._topics.get(topic)
        if t is None or partition >= len(t.partitions):
            return
        part = t.partitions[partition]
        if len(part.log) <= self.retention_messages:
            return
        floor = min((self.committed(topic, g, partition)
                     for g in self._groups.get(topic, ())), default=0)
        # Keep the horizon's worth of tail even below the floor so a
        # replay/debug read has recent context; trim the rest.
        end = part.base + len(part.log)
        part.trim(min(floor, end - self.retention_messages))


class Consumer:
    """One consumer group member over every partition of a topic.

    ``poll`` returns uncommitted messages; the caller processes them and
    ``commit``s — a crash before commit replays them (at-least-once), so
    lambdas carry their own dedup guard (deli log_offset, scriptorium seq).
    """

    def __init__(self, bus: MessageBus, topic: str, group: str) -> None:
        self._bus = bus
        self._topic = bus.topic(topic)
        self._topic_name = topic
        self.group = group
        # Register against the retention floor BEFORE the first poll: a
        # group the bus does not know about cannot pin the log, so it
        # must be visible before any trim could pass its position.
        # Duck-typed buses without retention (the native shuttle bus)
        # simply have no registry to join.
        register = getattr(bus, "register_group", None)
        if register is not None:
            register(topic, group)

    @property
    def num_partitions(self) -> int:
        return self._topic.num_partitions

    def poll(self, partition: int,
             max_messages: int | None = None) -> list[BusMessage]:
        start = self._bus.committed(self._topic_name, self.group, partition)
        return self._topic.read(partition, start, max_messages)

    def commit(self, partition: int, next_offset: int) -> None:
        self._bus.commit(self._topic_name, self.group, partition, next_offset)


class StateStore:
    """Durable key→document store (the reference's MongoDB for lambda
    checkpoints and the scriptorium op log)."""

    def __init__(self) -> None:
        self._data: dict[str, Any] = {}

    def get(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)

    def put(self, key: str, value: Any) -> None:
        self._data[key] = value

    def append(self, key: str, items: list) -> None:
        self._data.setdefault(key, []).extend(items)

    def keys(self, prefix: str = "") -> list[str]:
        return sorted(k for k in self._data if k.startswith(prefix))
