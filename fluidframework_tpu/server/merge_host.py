"""KernelMergeHost — device-resident converged document state on the server.

Reference parity: the *server-observed* hot loops of the reference — the
merge-tree sequenced apply path (packages/dds/merge-tree/src/mergeTree.ts:
1974 insertingWalk, 2626 markRangeRemoved, 2584 annotateRange) and the
SharedMap message fold (packages/dds/map/src/mapKernel.ts:510
tryProcessMessage) — hosted *behind the service seams* as one batched
device program, per SURVEY.md §7 / BASELINE.json: every (document,
channel) is a row of :class:`~fluidframework_tpu.ops.mergetree_kernel.
MergeState` or :class:`~fluidframework_tpu.ops.map_kernel.MapState`; a
service tick applies the pending sequenced ops of *all* channels in one
``apply_tick`` call (vmap over the row axis — the workload's data-parallel
axis, shardable over the device mesh via
:func:`fluidframework_tpu.parallel.mesh.shard_state`).

The host owns what the kernels cannot:

* string→int mappings (client id → slot lane, property key → key slot,
  value → interned id, text → pool offsets);
* capacity management — before each flush it checks
  :func:`~fluidframework_tpu.ops.mergetree_kernel.capacity_margin`,
  runs the device zamboni (:func:`~fluidframework_tpu.ops.
  mergetree_kernel.compact`) on rows under pressure, and grows the slot
  axes (doubling) when compaction is not enough;
* overflow routing — the remover-bitmask planes grow on demand (32
  writer slots per word, ``_MergePool.grow_overlap``) so the reference's
  own stress shapes (32-128 concurrent writers) stay device-served; only
  a channel whose writer set exceeds the configured ``max_client_slots``
  ceiling re-routes to the scalar
  :class:`~fluidframework_tpu.dds.mergetree.MergeEngine` (the "route
  over-capacity documents to the scalar path" contract from
  ``capacity_margin``'s docstring), and it is readmitted when zamboni
  shrinks its writer set back under the ceiling;
* summaries — converged channel contents materialized from device state.

Wire in: feed every sequenced message via :meth:`ingest` (LocalCollabServer
does this from its broadcast path; RouterliciousService via the merger
lambda in routerlicious.py). Ops buffer host-side and hit the device in
ticks — either when ``pending_ops`` crosses ``flush_threshold`` or when a
reader forces :meth:`flush`.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..dds.mergetree import Marker, MergeEngine, Segment
from ..dds.tree_core import ROOT_ID, VALID, Transaction, TreeSnapshot
from ..ops import map_kernel as mk
from ..ops import matrix_kernel as mxk
from ..ops import matrix_pallas as mxp
from ..ops import mergetree_blocks as mtb
from ..ops import mergetree_blocks_pallas as mtbp
from ..ops import mergetree_kernel as mtk
from ..ops import mergetree_pallas as mtp
from ..ops import tree_kernel as tk
from ..protocol.messages import MessageType, SequencedDocumentMessage
from ..utils import faults
from .kernel_host import _next_pow2, _tick_k

_MERGE_OPS = frozenset({"insert", "remove", "annotate", "group"})
_MAP_OPS = frozenset({"set", "delete", "clear"})

# Text pools are append-only; once a row's pool churn passes this mark the
# host repacks it down to the referenced slices (zamboni for text bytes).
_TEXT_REPACK_MIN = 1 << 20
# Tree channels trim their applied edit-log prefix into a materialized
# base snapshot once it outgrows this (the overflow fallback replays
# base + remaining log).
_TREE_LOG_TRIM = 512

# A marker occupies one pool char; stripped at materialization. Real text
# never contains NUL (the wire format is JSON-ish strings).
_MARKER_CHAR = "\x00"


class ChannelKey(NamedTuple):
    doc_id: str
    datastore: str
    channel: str


class _MergeRow:
    __slots__ = ("pool", "row", "client_slots", "key_slots", "pending",
                 "raw_log", "scalar", "min_seq", "last_seq",
                 "repack_at", "applied_seq", "applied_min_seq",
                 "readmit_seen_min", "mega_idle")

    def __init__(self) -> None:
        self.pool: "_MergePool | None" = None
        self.row = -1
        self.client_slots: dict[str, int] = {}
        self.key_slots: dict[str, int] = {}
        self.pending: list[dict] = []
        # Sequenced ops NOT YET applied on device (subop, seq, ref_seq,
        # client) — trimmed at every flush; the scalar-fallback replay
        # source is the device row itself (seeded exactly) plus this tail,
        # so a long-lived document's host memory stays bounded.
        self.raw_log: list[tuple[dict, int, int, str]] = []
        self.scalar: MergeEngine | None = None
        self.min_seq = 0
        self.last_seq = 0
        # Frontier the DEVICE row reflects (advances when raw_log trims):
        # the scalar seed starts here, then replays the unapplied tail.
        self.applied_seq = 0
        self.applied_min_seq = 0
        # Text-pool churn level that triggers the next repack attempt.
        self.repack_at = _TEXT_REPACK_MIN
        # min_seq at the last failed readmission attempt (scalar rows):
        # the writer set only shrinks when the window advances, so a
        # rescan before then is wasted work.
        self.readmit_seen_min = -1
        # Flushes since a mega-promoted row last had pending ops — the
        # cooling signal maybe_demote_megadocs keys on.
        self.mega_idle = 0


class _MapRow:
    __slots__ = ("row", "key_slots", "pending", "last_seq",
                 "literal_values")

    def __init__(self, row: int) -> None:
        self.row = row
        self.key_slots: dict[str, int] = {}
        self.pending: list[dict] = []
        self.last_seq = 0
        # Storm channels (server/storm.py) carry literal small-int values
        # in the op words instead of interned ids; they reject dict-path
        # traffic, so one row is always one mode.
        self.literal_values = False


class _MatrixRow:
    __slots__ = ("row", "client_slots", "pending", "raw_log", "scalar",
                 "last_seq", "min_seq", "next_row_handle",
                 "next_col_handle", "applied_seq", "applied_min_seq",
                 "last_vec_seq")

    def __init__(self, row: int) -> None:
        self.row = row
        self.client_slots: dict[str, int] = {}
        self.pending: list[dict] = []
        # Ops NOT YET applied on device (channel_op, seq, ref_seq, client)
        # — trimmed at every flush; the fallback seeds from the device row
        # and replays only this tail (bounded host memory).
        self.raw_log: list[tuple[dict, int, int, str]] = []
        self.scalar: tuple | None = None  # (rows vec, cols vec, cells dict)
        self.last_seq = 0
        self.min_seq = 0
        self.applied_seq = 0
        self.applied_min_seq = 0
        self.next_row_handle = 0
        self.next_col_handle = 0
        # Seq of the newest structural (vector) op — the cell-run fast
        # path is exact only when every cell's refSeq covers it.
        self.last_vec_seq = 0


class _TreeRow:
    """Host bookkeeping for one device-served SharedTree channel: string id
    → slot interning (the device stores only slots), per-row trait-label
    interning, and the sequenced-edit log that seeds the scalar fallback."""

    __slots__ = ("row", "slot_of", "info_of", "trait_ids", "trait_rev",
                 "free", "next_slot", "pending", "raw_log", "scalar",
                 "last_seq", "base")

    def __init__(self, row: int) -> None:
        self.row = row
        self.slot_of: dict[str, int] = {ROOT_ID: 0}
        self.info_of: dict[int, tuple[str, str]] = {0: (ROOT_ID, "root")}
        self.trait_ids: dict[str, int] = {}
        self.trait_rev: list[str] = []
        self.free: list[int] = []
        self.next_slot = 1
        self.pending: list[dict] = []
        # Sequenced edits since ``base`` — the exact replay source if this
        # channel leaves the device (unsupported edit shape / rank
        # overflow). At clean flush boundaries an over-long applied prefix
        # folds into ``base`` (a device-materialized snapshot), bounding
        # host memory; the fallback replays base + remaining log.
        self.raw_log: list[dict] = []
        self.base: dict | None = None  # serialized TreeSnapshot
        self.scalar: TreeSnapshot | None = None
        self.last_seq = 0


def _pad_axis(a, axis: int, extra: int, fill):
    a = np.asarray(a)
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, extra)
    return np.pad(a, widths, constant_values=fill)


def _next_pow2_width(cur: int, need: int) -> int:
    """Doubling growth policy shared by every plane-width axis (props,
    overlap words, map/tree slots): the smallest pow2 multiple of ``cur``
    that fits ``need``."""
    while cur < need:
        cur *= 2
    return cur


def _overlap_slots(words: np.ndarray) -> list[int]:
    """Set bits of one slot's overlap words → client slot indices. Words
    are i32 with the sign bit as a payload bit (slot 31 of each word)."""
    out = []
    for w, word in enumerate(np.asarray(words, np.int32).reshape(-1)):
        bits = int(np.uint32(word))  # sign bit → bit 31, not a sign
        base = 32 * w
        while bits:
            low = bits & -bits
            out.append(base + low.bit_length() - 1)
            bits ^= low
    return out


def _set_overlap_bit(words_row: np.ndarray, slot: int) -> None:
    """Set client ``slot``'s bit in an [S?, W] i32 word vector (in place),
    wrapping bit 31 through the sign bit."""
    words_row[slot >> 5] |= np.uint32(1 << (slot & 31)).astype(np.int32)


_MERGE_FILL = dict(valid=False, length=0, ins_seq=0, ins_client=-1,
                   rem_seq=int(mtk.NONE_SEQ), rem_client=-1, rem_overlap=0,
                   pool_start=0, prop_val=0, count=0)
_MAP_FILL = dict(present=False, value=0, vseq=-1, cleared_seq=-1)


class _MergePool:
    """One device MergeState for channels in the same segment-size bucket.

    Bucketed ragged batching (SURVEY §5.7): documents vary wildly in
    segment count, and a single [B, S] table pads EVERY row to the largest
    document's S. Buckets keyed by pow2 slot count bound the padding waste
    to 2×: a channel lives in the smallest bucket that fits it and
    migrates up (host round-trip, rare — doubling) when compaction can no
    longer make room. Each flush issues one apply_tick per dirty bucket.
    """

    #: Per-field blank values of the state class (subclasses override
    #: alongside _make_state) and the trailing feature axis the prop /
    #: overlap planes grow on ([B, S, F] = 2; the block table's
    #: [B, NB, Bk, F] = 3).
    _FILL = _MERGE_FILL
    _FEATURE_AXIS = 2

    def __init__(self, slots: int, num_props: int,
                 row_capacity: int = 8, overlap_words: int = 1) -> None:
        self.slots = slots
        self.num_props = num_props
        self.overlap_words = max(1, overlap_words)
        self.capacity = max(1, row_capacity)
        self.state = self._make_state()
        self.text = mtk.TextPool(self.capacity)
        self.members: list[_MergeRow | None] = []
        self.free: list[int] = []

    def _make_state(self):
        return mtk.init_state(self.capacity, self.slots, self.num_props,
                              self.overlap_words)

    @property
    def client_capacity(self) -> int:
        """Distinct writer slots the overlap planes can track."""
        return mtk.OVERLAP_WORD_BITS * self.overlap_words

    def alloc(self, mrow: _MergeRow) -> None:
        if self.free:
            row = self.free.pop()
            self.members[row] = mrow
        else:
            row = len(self.members)
            if row >= self.capacity:
                self._grow_rows()
            self.members.append(mrow)
        mrow.pool, mrow.row = self, row

    def release(self, row: int) -> None:
        """Blank a device row and recycle its index."""
        self.members[row] = None
        cls = type(self.state)
        self.state = self.place(cls(**{
            f: getattr(self.state, f).at[row].set(self._FILL[f])
            for f in cls._fields}))
        self.text.chunks[row] = []
        self.text.used[row] = 0
        self.free.append(row)

    def _grow_rows(self) -> None:
        old = self.capacity
        self.capacity = old * 2
        cls = type(self.state)
        self.state = self.place(jax.device_put(cls(**{
            f: _pad_axis(getattr(self.state, f), 0, old, self._FILL[f])
            for f in cls._fields})))
        self.text.chunks += [[] for _ in range(old)]
        self.text.used += [0] * old
        # members stays shorter than capacity; alloc() grows it by append

    def grow_props(self, need: int) -> None:
        new = _next_pow2_width(self.num_props, need)
        if new == self.num_props:
            return
        extra = new - self.num_props
        self.state = self.place(self.state._replace(prop_val=jnp.asarray(
            _pad_axis(self.state.prop_val, self._FEATURE_AXIS, extra, 0))))
        self.num_props = new

    def grow_overlap(self, need_words: int) -> None:
        """Widen the remover-bitmask planes (32 more writer slots per
        word) — the per-pool analog of grow_props. Documents with > 32
        distinct writers in their collab window pay for the extra planes;
        everyone else stays at one word."""
        new = _next_pow2_width(self.overlap_words, need_words)
        if new == self.overlap_words:
            return
        extra = new - self.overlap_words
        self.state = self.place(self.state._replace(
            rem_overlap=jnp.asarray(
                _pad_axis(self.state.rem_overlap, self._FEATURE_AXIS,
                          extra, 0))))
        self.overlap_words = new

    def row_arrays(self, row: int) -> dict[str, np.ndarray]:
        """Host copies of one row's planes (migration source)."""
        return {f: np.asarray(getattr(self.state, f)[row])
                for f in mtk.MergeState._fields}

    def write_row(self, row: int, arrays: dict[str, np.ndarray]) -> None:
        """Install planes (padded by the caller) into a row."""
        self.state = self.place(mtk.MergeState(**{
            f: getattr(self.state, f).at[row].set(arrays[f])
            for f in mtk.MergeState._fields}))

    # -- device-dispatch / layout hooks (overridden by the block and
    # sharded pools; the host talks to pools only through these seams) --------

    def apply(self, batch: mtk.MergeOpBatch):
        return mtp.apply_tick_best(self.state, batch)

    def compact_state(self, min_seq, coalesce: bool = False):
        return mtk.compact(self.state, min_seq, coalesce)

    def place(self, state):
        return state

    def margins(self) -> np.ndarray:
        """Free slots per row (worst-case admission check input)."""
        return mtk.capacity_margin(self.state)

    def pre_tick(self, need: np.ndarray) -> None:
        """Layout maintenance before a tick (block pools rebalance when
        a row's fullest block cannot absorb its worst-case tick)."""

    def take_overflow(self) -> np.ndarray | None:
        """Per-row first-overflow op index of the last apply (block
        pools only; None = the layout cannot overflow mid-tick)."""
        return None

    def materialize_row(self, row: int) -> str:
        return mtk.materialize(self.state, self.text, row)

    def set_pool_start(self, row: int, starts: np.ndarray) -> None:
        """Install a repacked pool_start plane (flat document order)."""
        self.state = self.place(self.state._replace(
            pool_start=self.state.pool_start.at[row].set(
                jnp.asarray(starts))))


_BLOCK_FILL = dict(length=0, ins_seq=0, ins_client=-1,
                   rem_seq=int(mtk.NONE_SEQ), rem_client=-1,
                   rem_overlap=0, pool_start=0, prop_val=0,
                   blk_count=0, blk_live_len=0, blk_max_seq=0,
                   blk_tomb=0, count=0)


class _BlockMergePool(_MergePool):
    """A bucket served by the block-structured table
    (ops/mergetree_blocks.py): O(S/Bk + Bk) per-op apply instead of the
    flat kernel's O(S) — THE text serving path (ISSUE 2 / VERDICT r5
    next-round #1). Bucket capacity is NB blocks × Bk slots; the host
    seams exchange FLAT document-order arrays (gaps = block tails), so
    migration, scalar seeding and the text repack are layout-agnostic.

    Overflow contract: an op whose target block is full freezes its doc
    at that op (atomic, first index reported); ``_flush_merge`` replays
    the tail through the flat kernel and re-blocks — exact, just slower,
    and rare because ``pre_tick`` rebalances any row whose fullest block
    cannot absorb the worst case (2 slots/op) of its pending tick."""

    BK = 128  # lane-width blocks (Bk); buckets below 128 use one block
    _FILL = _BLOCK_FILL
    _FEATURE_AXIS = 3  # [B, NB, Bk, F] prop/overlap planes

    def __init__(self, slots: int, num_props: int,
                 row_capacity: int = 8, overlap_words: int = 1,
                 block_slots: int | None = None) -> None:
        # ``block_slots`` overrides the lane-width default Bk — the
        # geometry-autotune seam (head-concentrated streams trade NB for
        # a larger Bk so the hot block absorbs several ticks per
        # rebalance); snapshots record it so import_state re-blocks
        # identically.
        self.bk = min(block_slots or self.BK, slots)
        self.nb = max(1, slots // self.bk)
        #: pre_tick trigger telemetry: (flush gates seen, rebalances
        #: fired) — the fire RATE is the observed head-concentration
        #: input of KernelMergeHost.autotune_block_geometry.
        self.pre_ticks = 0
        self.rebalance_fires = 0
        super().__init__(slots, num_props, row_capacity, overlap_words)

    def _make_state(self):
        return mtb.init_state(self.capacity, self.nb, self.bk,
                              self.num_props, self.overlap_words)

    def row_arrays(self, row: int) -> dict[str, np.ndarray]:
        """Flat document-order planes of one row (gaps masked to fills)."""
        s = self.state
        flat = self.nb * self.bk
        bc = np.asarray(s.blk_count[row])
        valid = (np.arange(self.bk)[None, :] < bc[:, None]).reshape(-1)
        out: dict[str, np.ndarray] = {"valid": valid,
                                      "count": np.asarray(s.count[row])}
        for f in ("length", "ins_seq", "ins_client", "rem_seq",
                  "rem_client", "pool_start"):
            plane = np.asarray(getattr(s, f)[row]).reshape(flat).copy()
            plane[~valid] = _MERGE_FILL[f]
            out[f] = plane
        for f in ("rem_overlap", "prop_val"):
            plane = np.asarray(getattr(s, f)[row]).reshape(flat, -1).copy()
            plane[~valid] = 0
            out[f] = plane
        return out

    def write_row(self, row: int, arrays: dict[str, np.ndarray]) -> None:
        blocked = mtb.host_block_row(arrays, self.nb, self.bk)
        self.state = self.place(mtb.BlockMergeState(**{
            f: getattr(self.state, f).at[row].set(blocked[f])
            for f in mtb.BlockMergeState._fields}))

    def apply(self, batch: mtk.MergeOpBatch):
        state, overflow = mtbp.apply_tick_blocks_best(self.state, batch)
        self.last_overflow = np.asarray(overflow)
        return state

    def compact_state(self, min_seq, coalesce: bool = False):
        return mtb.rebalance(self.state, min_seq, coalesce)

    def margins(self) -> np.ndarray:
        return mtb.capacity_margin(self.state)

    def pre_tick(self, need: np.ndarray) -> bool:
        """Rebalance when any pending row's fullest block could not take
        its whole tick (all ops landing in one block is the worst case).
        The device re-decides with the incremental ladder
        (mtb.maybe_rebalance): overfull blocks spill into neighbors,
        tombstone drops defer behind the blk_tomb pressure threshold,
        and only an infeasible spill pays the full pack + uniform
        redistribution. Returns whether the host trigger fired (the
        autotune fire-rate signal)."""
        self.pre_ticks += 1
        fills = mtb.max_block_fill(self.state)
        if not np.any(need + fills > self.bk):
            return False
        self.rebalance_fires += 1
        min_seq = np.full(self.capacity, -1, np.int32)
        for r in self.members:
            if r is not None:
                min_seq[r.row] = r.min_seq
        # Chaos kill class "mid-rebalance": the layout is about to move;
        # a crash here loses only volatile device state (the durable log
        # + snapshot replay rebuilds the row byte-identically).
        faults.crashpoint("pool.mid_rebalance")
        # The pow2-bucketed tick width keeps 2*kk + 2 >= need (the
        # device headroom check is at least as conservative as the host
        # gate above) without a fresh jit instance per flush shape.
        kk = _tick_k(int(need.max() - 2 + 1) // 2)
        self.state = self.place(mtb.maybe_rebalance(
            self.state, jnp.asarray(min_seq), kk))
        return True

    def take_overflow(self) -> np.ndarray | None:
        out = getattr(self, "last_overflow", None)
        self.last_overflow = None
        return out

    def fire_rate(self) -> float:
        """Observed rebalance fire rate (fires per flush gate) — the
        head-concentration estimate geometry autotuning keys on."""
        if not self.pre_ticks:
            return 0.0
        return self.rebalance_fires / self.pre_ticks

    def retune(self, block_slots: int) -> None:
        """Re-block the WHOLE pool to a new Bk (same total slots, so
        every capacity contract is unchanged): pack each row's occupied
        slots and redistribute uniformly over the new [NB', Bk'] grid —
        a pure re-layout through the packed flat form (document order,
        summaries-from-planes and text pools untouched). Deterministic
        in (state, block_slots), so a replay that re-runs the same
        retune re-blocks byte-identically."""
        bk = min(block_slots, self.slots)
        nb = max(1, self.slots // bk)
        if nb * bk != self.slots:
            raise ValueError(
                f"block_slots {bk} does not divide pool slots "
                f"{self.slots}")
        if (nb, bk) == (self.nb, self.bk):
            return
        # Chaos kill class "mid-retune": the layout is about to move
        # wholesale; a crash here loses only volatile device state (the
        # durable-log replay rebuilds the rows, re-deciding the same
        # geometry).
        faults.crashpoint("pool.mid_retune")
        packed = mtb.to_flat(self.state, slots=self.slots)
        self.state = self.place(mtb.from_flat(packed, nb))
        self.nb, self.bk = nb, bk
        self.pre_ticks = 0
        self.rebalance_fires = 0

    def materialize_row(self, row: int) -> str:
        return mtb.materialize(self.state, self.text, row)

    def set_pool_start(self, row: int, starts: np.ndarray) -> None:
        self.state = self.place(self.state._replace(
            pool_start=self.state.pool_start.at[row].set(jnp.asarray(
                np.asarray(starts).reshape(self.nb, self.bk)))))


class _ShardedMergePool(_MergePool):
    """A bucket whose SEGMENT axis is sharded over a device mesh — the
    serving home for documents too large for one chip's table
    (ops/mergetree_sharded.py, the sequence-parallel path). Everything
    else about the pool (rows, text, migration) is inherited; device
    dispatch goes through the collective kernel and every host-side
    rebuild is re-placed with the segment sharding.

    Two populations live in pools of this class: documents whose
    segment tables OUTGREW one chip (``sharded_slot_threshold``, the
    size tier) and documents PROMOTED for write rate (``mega=True`` —
    the mega-doc residency class: not necessarily huge, but co-written
    hard enough that the merge walk itself wants device lanes)."""

    def __init__(self, slots: int, num_props: int, mesh,
                 row_capacity: int = 1, overlap_words: int = 1,
                 mega: bool = False) -> None:
        from ..ops import mergetree_sharded as mts
        self._mts = mts
        self.mesh = mesh
        self.mega = mega
        super().__init__(slots, num_props, row_capacity, overlap_words)
        self.state = self.place(self.state)

    def apply(self, batch: mtk.MergeOpBatch) -> mtk.MergeState:
        return self._mts.apply_tick_sharded(self.state, batch, self.mesh)

    def compact_state(self, min_seq, coalesce: bool = False
                      ) -> mtk.MergeState:
        return self.place(mtk.compact(self.state, min_seq, coalesce))

    def place(self, state: mtk.MergeState) -> mtk.MergeState:
        return self._mts.shard_merge_state(state, self.mesh)


class KernelMergeHost:
    """Batched device host for the merge-tree and map apply kernels."""

    def __init__(self, merge_slots: int = 128, map_slots: int = 32,
                 num_props: int = 4, row_capacity: int = 8,
                 flush_threshold: int = 256, metrics=None,
                 seg_mesh=None, sharded_slot_threshold: int = 65536,
                 tree_slots: int = 32,
                 max_client_slots: int = 1024,
                 megadoc_writer_threshold: int | None = None,
                 megadoc_demote_idle_flushes: int = 64) -> None:
        from ..utils import MetricsRegistry
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        # Sequence-parallel escape hatch: documents whose segment tables
        # outgrow one chip migrate into pools whose SEGMENT axis is
        # sharded over ``seg_mesh`` (ops/mergetree_sharded.py) instead of
        # growing a single-chip table without bound. Misconfiguration
        # must fail HERE, not at the first flush mid-serving: pool slot
        # counts are powers of two, so the mesh size must be one too, and
        # every shard needs >= 2 slots.
        self.seg_mesh = seg_mesh
        if seg_mesh is not None:
            n_shards = seg_mesh.devices.size
            if n_shards & (n_shards - 1) != 0:
                # ValueError, not assert: python -O must not defer this
                # to the first sharded flush mid-serving.
                raise ValueError(
                    f"seg_mesh size {n_shards} must be a power of two "
                    "(pool slot counts are)")
            sharded_slot_threshold = max(sharded_slot_threshold,
                                         2 * n_shards)
        self.sharded_slot_threshold = max(8, sharded_slot_threshold)
        self._row_capacity = max(1, row_capacity)
        self._map_capacity = max(1, row_capacity)
        self._merge_slots = max(8, merge_slots)  # smallest bucket size
        self._map_slots = max(4, map_slots)
        self._num_props = max(1, num_props)
        self.flush_threshold = flush_threshold
        # Ceiling on distinct device-tracked writers per channel: the
        # overlap planes grow on demand (32 slots/word) up to here; only
        # beyond it does a channel route to the scalar path. The reference
        # caps clients/doc at 1,000,000 (config.json:39) but its own
        # stress shapes are 32-128 writers — the default keeps worst-case
        # plane memory bounded at 32 words.
        self.max_client_slots = max(mtk.OVERLAP_WORD_BITS,
                                    max_client_slots)

        # Merge channels live in pow2-bucketed pools (bucketed ragged
        # batching); maps are uniform-small and keep one state; matrices
        # (two embedded merge states + a cell table) lazily allocate one.
        self._merge_pools: dict[int, _MergePool] = {}
        # Mega-doc pools (the round-15 residency class): sequence-
        # parallel pools for PROMOTED docs — same _ShardedMergePool
        # machinery as the size tier, keyed separately because a mega
        # doc at (say) 128 slots must not hijack the block bucket every
        # ordinary 128-slot doc serves from. Promotion/demotion moves a
        # row between the tiers through the exact packed-flat seam
        # (promote_merge_row / demote_merge_row).
        self._mega_pools: dict[int, _ShardedMergePool] = {}
        # Auto-promotion by OBSERVED writer count (None = explicit-only):
        # a doc whose device-tracked writer set crosses the threshold
        # promotes at the next flush; a promoted row idle for
        # ``megadoc_demote_idle_flushes`` flushes demotes back.
        self.megadoc_writer_threshold = megadoc_writer_threshold
        self.megadoc_demote_idle_flushes = max(
            1, megadoc_demote_idle_flushes)
        self._xstate = mk.init_state(self._map_capacity, self._map_slots)
        self._matrix_state: mxk.MatrixState | None = None
        self._matrix_capacity = max(1, row_capacity)
        self._matrix_vec_slots = 64
        self._matrix_cell_slots = 256
        self._matrix_overlap_words = 1
        self._matrix_rows: dict[ChannelKey, _MatrixRow] = {}

        # Tree channels share one pooled TreeState [B, N] (uniform slot
        # axis; both axes grow pow2) — SharedTree.processCore behind the
        # service (SharedTree.ts:446, Checkout.ts:172 rebase).
        self._tree_state: tk.TreeState | None = None
        self._tree_capacity = max(1, row_capacity)
        self._tree_slots = max(8, tree_slots)
        self._tree_rows: dict[ChannelKey, _TreeRow] = {}

        self._merge_rows: dict[ChannelKey, _MergeRow] = {}
        self._map_rows: dict[ChannelKey, _MapRow] = {}
        # Map-row recycling (doc residency): released rows reissue before
        # the high-water counter grows the state — see release_map_row.
        self._free_map_rows: list[int] = []
        self._map_row_count = 0
        # Shared value interning (map values + annotate values). Id 0 is
        # reserved for "absent"/None; ids index _val_rev.
        self._vals: dict[str, int] = {}
        self._val_rev: list[Any] = [None]
        self._pending_ops = 0
        # Counters surfaced by the telemetry layer (ops served by the
        # device path vs routed to the scalar fallback).
        self.stats = {"device_ops": 0, "scalar_ops": 0, "flushes": 0,
                      "compactions": 0, "overflow_routed": 0,
                      "migrations": 0, "readmissions": 0,
                      "block_overflow_replays": 0,
                      "quarantined_channels": 0,
                      "rebalances": 0, "geometry_retunes": 0,
                      "megadoc_promotions": 0, "megadoc_demotions": 0}

    # -- interning -------------------------------------------------------------

    def _intern(self, value: Any) -> int:
        if value is None:
            return 0
        key = repr(value)
        vid = self._vals.get(key)
        if vid is None:
            vid = len(self._val_rev)
            self._vals[key] = vid
            self._val_rev.append(value)
        return vid

    # -- row allocation / growth -----------------------------------------------

    def _pool_for(self, slots: int) -> _MergePool:
        slots = max(_next_pow2(slots), self._merge_slots)
        pool = self._merge_pools.get(slots)
        if pool is None:
            if (self.seg_mesh is not None
                    and slots >= self.sharded_slot_threshold):
                pool = _ShardedMergePool(slots, self._num_props,
                                         self.seg_mesh)
            else:
                # The block-structured table IS the single-chip serving
                # path; only the sequence-parallel pools stay flat (the
                # segment axis shards, the block axis would not).
                pool = _BlockMergePool(slots, self._num_props,
                                       self._row_capacity)
            self._merge_pools[slots] = pool
        return pool

    def _merge_row(self, key: ChannelKey) -> _MergeRow:
        state = self._merge_rows.get(key)
        if state is None:
            state = _MergeRow()
            self._pool_for(self._merge_slots).alloc(state)
            self._merge_rows[key] = state
        return state

    def _migrate_merge_row(self, mrow: _MergeRow, target_slots: int) -> None:
        """Move a channel to a bigger bucket (its segment table no longer
        fits even after compaction). One host round-trip per migration;
        doubling makes them geometrically rare. A mega-promoted row
        grows WITHIN the mega tier — capacity pressure must never
        silently demote the write-rate placement."""
        if getattr(mrow.pool, "mega", False):
            self._move_row(mrow, self._mega_pool_for(target_slots))
        else:
            self._move_row(mrow, self._pool_for(target_slots))
        self.stats["migrations"] += 1

    def _move_row(self, mrow: _MergeRow, dst_pool: _MergePool) -> None:
        """Relocate one channel's row between pools through the exact
        packed-flat seam (row_arrays → write_row — the host twin of
        ``from_block_state``/``from_flat``: block sources flatten to
        document order, block destinations re-block, flat↔flat installs
        verbatim). Layout-agnostic, so bucket migration, mega-doc
        promotion and demotion all share it; pending (not-yet-applied)
        ops ride along — their encodings index the row's text pool,
        which moves with the row."""
        src_pool, src_row = mrow.pool, mrow.row
        assert dst_pool is not src_pool
        if src_pool.num_props > dst_pool.num_props:
            dst_pool.grow_props(src_pool.num_props)
        if src_pool.overlap_words > dst_pool.overlap_words:
            dst_pool.grow_overlap(src_pool.overlap_words)
        arrays = src_pool.row_arrays(src_row)
        pad_s = dst_pool.slots - src_pool.slots
        out: dict[str, np.ndarray] = {}
        for f, a in arrays.items():
            if f == "count":
                out[f] = a
            elif f == "prop_val":
                padded = _pad_axis(a, 0, pad_s, 0)
                out[f] = _pad_axis(padded, 1,
                                   dst_pool.num_props - a.shape[1], 0)
            elif f == "rem_overlap":
                padded = _pad_axis(a, 0, pad_s, 0)
                out[f] = _pad_axis(padded, 1,
                                   dst_pool.overlap_words - a.shape[1], 0)
            else:
                out[f] = _pad_axis(a, 0, pad_s, _MERGE_FILL[f])
        dst_pool.alloc(mrow)
        dst_pool.write_row(mrow.row, out)
        dst_pool.text.chunks[mrow.row] = src_pool.text.chunks[src_row]
        dst_pool.text.used[mrow.row] = src_pool.text.used[src_row]
        src_pool.release(src_row)

    # -- mega-doc promotion (the round-15 residency class) ---------------------

    def _mega_pool_for(self, slots: int) -> _ShardedMergePool:
        assert self.seg_mesh is not None, "mega promotion needs a seg_mesh"
        slots = max(_next_pow2(slots), self._merge_slots,
                    2 * self.seg_mesh.devices.size)
        pool = self._mega_pools.get(slots)
        if pool is None:
            pool = _ShardedMergePool(slots, self._num_props,
                                     self.seg_mesh, mega=True)
            self._mega_pools[slots] = pool
        return pool

    def is_mega_row(self, key: ChannelKey) -> bool:
        row = self._merge_rows.get(key)
        return (row is not None and row.pool is not None
                and getattr(row.pool, "mega", False))

    def promote_merge_row(self, key: ChannelKey) -> None:
        """Mega-doc promotion: move one channel's segment table from its
        block bucket into a sequence-parallel pool — the segment axis
        placed ACROSS device lanes — through the packed-flat seam
        (:func:`ops.mergetree_sharded.from_block_state` is the kernel
        twin of this host move; the round-trip is exact and pinned by
        tests/test_megadoc_roundtrip.py). Pending ops ride along.
        Idempotent on an already-promoted row; scalar-routed channels
        refuse (there is no device row to shard)."""
        row = self._merge_rows[key]
        if row.scalar is not None:
            raise ValueError(
                f"{key} is scalar-routed; readmit before promoting")
        if getattr(row.pool, "mega", False):
            return
        dst = self._mega_pool_for(row.pool.slots)
        # Kill window: the layout is about to move wholesale; a crash
        # here loses only volatile device state (the durable log +
        # snapshot replay rebuilds the row and re-decides the same
        # promotion).
        faults.crashpoint("megadoc.mid_promotion")
        self._move_row(row, dst)
        row.mega_idle = 0
        self.stats["megadoc_promotions"] += 1
        self.metrics.counter("megadoc.text_promotions").inc()

    def demote_merge_row(self, key: ChannelKey) -> bool:
        """Demote a promoted channel back to its single-chip block
        bucket through ``mergetree_blocks.from_flat`` (the block pool's
        write_row re-blocks the packed document order exactly). A doc
        whose table genuinely exceeds ``sharded_slot_threshold`` stays
        sequence-parallel (that is the SIZE tier, not the write-rate
        tier) — returns False then."""
        row = self._merge_rows[key]
        if not getattr(row.pool, "mega", False):
            return False
        if row.pool.slots >= self.sharded_slot_threshold:
            return False
        faults.crashpoint("megadoc.mid_demotion")
        self._move_row(row, self._pool_for(row.pool.slots))
        row.mega_idle = 0
        self.stats["megadoc_demotions"] += 1
        self.metrics.counter("megadoc.text_demotions").inc()
        return True

    def maybe_adapt_megadocs(self) -> None:
        """Flush-cadence auto promotion/demotion from OBSERVED load:
        distinct writers in the PENDING tick promote (instantaneous
        concurrency, not the historical client table — slots never
        shrink, so the historical count would re-promote forever after
        one swarm), idle flushes demote. No-op unless
        ``megadoc_writer_threshold`` is armed and a seg_mesh exists."""
        if self.megadoc_writer_threshold is None or self.seg_mesh is None:
            return
        for key, row in list(self._merge_rows.items()):
            if row.scalar is not None or row.pool is None:
                continue
            if getattr(row.pool, "mega", False):
                row.mega_idle = 0 if row.pending else row.mega_idle + 1
                if row.mega_idle >= self.megadoc_demote_idle_flushes:
                    self.demote_merge_row(key)
            elif row.pending and len(
                    {op["client"] for op in row.pending}
                    ) >= self.megadoc_writer_threshold:
                self.promote_merge_row(key)

    def _map_row(self, key: ChannelKey) -> _MapRow:
        state = self._map_rows.get(key)
        if state is None:
            if self._free_map_rows:
                row = self._free_map_rows.pop()
            else:
                row = self._map_row_count
                if row >= self._map_capacity:
                    self._grow_map_rows()
                self._map_row_count += 1
            state = _MapRow(row)
            self._map_rows[key] = state
        return state

    def release_map_row(self, key: ChannelKey) -> int:
        """Free a map channel's device row (the eviction half of tiered
        doc residency): blank the planes back to init fills and recycle
        the index, so map capacity is bounded by the PEAK RESIDENT
        channel count. The caller owns durability — evict only after the
        row's snapshot is durable. Returns the freed row index."""
        state = self._map_rows.pop(key)
        assert not state.pending, (
            f"release_map_row({key}) with pending ops — flush first")
        row = state.row
        self._xstate = mk.MapState(
            **{f: getattr(self._xstate, f).at[row].set(_MAP_FILL[f])
               for f in mk.MapState._fields})
        self._free_map_rows.append(row)
        return row

    def _grow_map_rows(self) -> None:
        old = self._map_capacity
        self._map_capacity = old * 2
        self._xstate = jax.device_put(mk.MapState(**{
            f: _pad_axis(getattr(self._xstate, f), 0, old, _MAP_FILL[f])
            for f in mk.MapState._fields}))

    def _grow_map_slots(self, need: int) -> None:
        new = _next_pow2_width(self._map_slots, need)
        extra = new - self._map_slots
        self._xstate = jax.device_put(mk.MapState(**{
            f: (_pad_axis(getattr(self._xstate, f), 1, extra, _MAP_FILL[f])
                if f != "cleared_seq" else np.asarray(self._xstate.cleared_seq))
            for f in mk.MapState._fields}))
        self._map_slots = new

    # -- ingest ----------------------------------------------------------------

    def ingest(self, doc_id: str, message: SequencedDocumentMessage) -> None:
        """Feed one sequenced message. Non-channel-ops are ignored; merge and
        map channel ops are routed to their device rows."""
        if message.type != MessageType.OPERATION:
            return
        envelope = message.contents
        if not isinstance(envelope, dict) or "address" not in envelope:
            return
        inner = envelope.get("contents")
        if not isinstance(inner, dict) or "address" not in inner:
            return
        channel_op = inner.get("contents")
        if not isinstance(channel_op, dict) or "type" not in channel_op:
            return
        key = ChannelKey(doc_id, envelope["address"], inner["address"])
        kind = channel_op["type"]
        if "target" in channel_op:
            # Matrix ops carry a target axis/cell and reuse type names the
            # merge/map sets also use — route by shape FIRST.
            self._ingest_matrix(key, channel_op, message)
        elif kind == "edit" and "edit" in channel_op:
            self._ingest_tree(key, channel_op, message)
        elif kind in _MERGE_OPS:
            self._ingest_merge(key, channel_op, message)
        elif kind in _MAP_OPS:
            self._ingest_map(key, channel_op, message)
        if self._pending_ops >= self.flush_threshold:
            self.flush()

    def _ingest_merge(self, key: ChannelKey, channel_op: dict,
                      message: SequencedDocumentMessage) -> None:
        row = self._merge_row(key)
        seq = message.sequence_number
        if seq <= row.last_seq:
            return  # bus replay
        row.last_seq = seq
        row.min_seq = message.minimum_sequence_number
        ref_seq = message.reference_sequence_number
        client = message.client_id
        subops = (channel_op["ops"] if channel_op["type"] == "group"
                  else [channel_op])
        if row.scalar is not None:
            # Scalar-served: the engine is the state now; no log needed.
            for op in subops:
                row.scalar.apply_remote(op, seq, ref_seq, client)
            # The window advances here too: tombstones compact (zamboni)
            # and the live writer set can shrink back under the device
            # bitmask — the readmission check at flush watches for that.
            row.scalar.update_min_seq(message.minimum_sequence_number)
            self.stats["scalar_ops"] += len(subops)
            return
        for op in subops:
            row.raw_log.append((op, seq, ref_seq, client))
        if (client not in row.client_slots
                and len(row.client_slots) >= self.max_client_slots):
            self._route_to_scalar(key, row)
            self.stats["scalar_ops"] += len(subops)
            return
        slot = row.client_slots.setdefault(client, len(row.client_slots))
        if slot >= row.pool.client_capacity:
            row.pool.grow_overlap(mtk.overlap_words_for(slot + 1))
        for op in subops:
            base = dict(seq=seq, ref_seq=ref_seq, client=slot)
            if op["type"] == "insert":
                if "text" in op:
                    text = op["text"]
                elif "items" in op:
                    # Item-vector insert (e.g. permutation-vector handles):
                    # one placeholder char per item keeps every later
                    # position-based op resolving against correct visible
                    # lengths; item payloads are opaque to the text plane.
                    text = _MARKER_CHAR * len(op["items"])
                else:
                    text = _MARKER_CHAR
                enc = dict(base, kind=mtk.MT_INSERT, pos=op["pos"],
                           pool_start=row.pool.text.append(row.row, text),
                           text_len=len(text))
                row.pending.append(enc)
                self._pending_ops += 1
                # An insert may also carry initial props; they apply to the
                # fresh segment only, which at this seq is exactly the
                # inserted range.
                if op.get("props"):
                    self._encode_annotates(
                        row, base, op["pos"], op["pos"] + len(text),
                        op["props"])
            elif op["type"] == "remove":
                row.pending.append(dict(base, kind=mtk.MT_REMOVE,
                                        pos=op["start"], end=op["end"]))
                self._pending_ops += 1
            else:  # annotate
                self._encode_annotates(row, base, op["start"], op["end"],
                                       op["props"])

    def _encode_annotates(self, row: _MergeRow, base: dict, start: int,
                          end: int, props: dict) -> None:
        for prop_key, value in sorted(props.items()):
            kslot = row.key_slots.setdefault(prop_key, len(row.key_slots))
            row.pending.append(dict(base, kind=mtk.MT_ANNOTATE, pos=start,
                                    end=end, prop_key=kslot,
                                    prop_val=self._intern(value)))
            self._pending_ops += 1

    def _seed_merge_engine(self, row: _MergeRow) -> MergeEngine:
        """Exact scalar twin of a device merge row: every table slot —
        live AND tombstoned-in-window — becomes a Segment with its insert
        seq/client, removal seq/client/overlap set and props, so future
        position transforms resolve identically. O(row), paid only when a
        channel leaves the device; replaces replaying full history."""
        arrays = row.pool.row_arrays(row.row)
        buffer = row.pool.text.buffer(row.row)
        slot_rev = {s: c for c, s in row.client_slots.items()}
        key_rev = {s: k for k, s in row.key_slots.items()}
        engine = MergeEngine(local_client=None)
        engine.current_seq = row.applied_seq
        engine.min_seq = row.applied_min_seq
        none_seq = int(mtk.NONE_SEQ)
        for i in range(arrays["valid"].shape[0]):
            if not arrays["valid"][i]:
                continue
            length = int(arrays["length"][i])
            if length == 0:
                continue  # transient zero-length slot: nothing to carry
            start = int(arrays["pool_start"][i])
            text = buffer[start:start + length]
            if text == _MARKER_CHAR * length:
                # Marker / item-run segment (encoded as NUL chars; item
                # payloads are opaque to the server). A non-str content
                # keeps text() from serving NULs; placeholders preserve
                # the position-space length.
                content: Any = Marker() if length == 1 \
                    else tuple([None] * length)
            else:
                content = text
            rem_seq = int(arrays["rem_seq"][i])
            overlap = {slot_rev[s]
                       for s in _overlap_slots(arrays["rem_overlap"][i])
                       if s in slot_rev}
            props = {key_rev[p]: self._val_rev[int(arrays["prop_val"][i, p])]
                     for p in range(arrays["prop_val"].shape[1])
                     if int(arrays["prop_val"][i, p]) and p in key_rev}
            engine.segments.append(Segment(
                content=content,
                seq=int(arrays["ins_seq"][i]),
                client=slot_rev.get(int(arrays["ins_client"][i])),
                removed_seq=None if rem_seq == none_seq else rem_seq,
                removed_client=slot_rev.get(int(arrays["rem_client"][i])),
                removed_overlap=overlap,
                props=props or None,
            ))
        return engine

    def _route_to_scalar(self, key: ChannelKey, row: _MergeRow) -> None:
        """Client-slot bitmask exhausted: seed the scalar engine from the
        device row (exact, O(row)) and replay only the unapplied tail."""
        engine = self._seed_merge_engine(row)
        for op, seq, ref_seq, client in row.raw_log:
            engine.apply_remote(op, seq, ref_seq, client)
        self._pending_ops -= len(row.pending)
        self.stats["overflow_routed"] += 1
        self._demote_row_to_scalar(row, engine)

    def _demote_row_to_scalar(self, row: _MergeRow, engine) -> None:
        """Shared tail of the device→scalar escapes (slot overflow and
        per-row quarantine): the engine becomes the channel state and
        the device row is surrendered."""
        row.scalar = engine
        row.raw_log = []  # the engine IS the state from here on
        row.pending = []
        row.applied_seq = row.last_seq
        row.applied_min_seq = row.min_seq
        # Release the abandoned device row: blanking its valid mask keeps
        # later apply_tick/compact passes from dragging stale segments.
        row.pool.release(row.row)
        row.pool, row.row = None, -1
        self._export_stats()

    # -- matrix channels (matrix.ts:547 behind the service) --------------------

    def _matrix_row(self, key: ChannelKey) -> _MatrixRow:
        state = self._matrix_rows.get(key)
        if state is None:
            row = len(self._matrix_rows)
            if row >= self._matrix_capacity:
                self._grow_matrix_rows()
            state = _MatrixRow(row)
            self._matrix_rows[key] = state
        return state

    def _ingest_matrix(self, key: ChannelKey, channel_op: dict,
                       message: SequencedDocumentMessage) -> None:
        row = self._matrix_row(key)
        seq = message.sequence_number
        if seq <= row.last_seq:
            return  # bus replay
        row.last_seq = seq
        row.min_seq = message.minimum_sequence_number
        ref_seq = message.reference_sequence_number
        client = message.client_id
        if row.scalar is not None:
            # Scalar-served: no device state to rebuild later, no log.
            self._matrix_scalar_apply(row, channel_op, seq, ref_seq, client)
            self.stats["scalar_ops"] += 1
            return
        row.raw_log.append((channel_op, seq, ref_seq, client))
        if (client not in row.client_slots
                and len(row.client_slots) >= self.max_client_slots):
            self._route_matrix_to_scalar(row)
            self.stats["scalar_ops"] += 1
            return
        slot = row.client_slots.setdefault(client, len(row.client_slots))
        if slot >= mtk.OVERLAP_WORD_BITS * self._matrix_overlap_words:
            self._grow_matrix_overlap(mtk.overlap_words_for(slot + 1))

        def alloc(axis):
            def inner(count):
                base = getattr(row, axis)
                setattr(row, axis, base + count)
                return base
            return inner

        encoded = mxk.encode_matrix_op(
            channel_op, dict(seq=seq, ref_seq=ref_seq, client=slot),
            alloc("next_row_handle"), alloc("next_col_handle"),
            self._intern)
        row.pending.extend(encoded)
        for enc in encoded:
            if enc["target"] != mxk.MX_CELL:
                row.last_vec_seq = max(row.last_vec_seq, enc["seq"])
        self._pending_ops += len(encoded)

    def _seed_matrix_scalar(self, row: _MatrixRow) -> tuple:
        """Exact scalar twin of a device matrix row: the two embedded
        merge states become PermutationVectors (handle runs from
        pool_start), the cell table becomes the LWW dict."""
        from ..dds.matrix import PermutationVector
        s = self._matrix_state
        slot_rev = {sl: c for c, sl in row.client_slots.items()}
        none_seq = int(mtk.NONE_SEQ)

        def seed_vec(ms: mtk.MergeState,
                     next_handle: int) -> PermutationVector:
            vec = PermutationVector(None)
            # Handle allocation continues where the host's device-path
            # counter left off (a fresh vector restarting at 0 would
            # collide new runs with live handles).
            vec.next_handle = next_handle
            engine = vec.engine
            engine.current_seq = row.applied_seq
            engine.min_seq = row.applied_min_seq
            arrays = {f: np.asarray(getattr(ms, f)[row.row])
                      for f in mtk.MergeState._fields if f != "count"}
            for i in range(arrays["valid"].shape[0]):
                if not arrays["valid"][i] or arrays["length"][i] == 0:
                    continue
                base = int(arrays["pool_start"][i])
                length = int(arrays["length"][i])
                rem = int(arrays["rem_seq"][i])
                overlap = {slot_rev[c]
                           for c in _overlap_slots(arrays["rem_overlap"][i])
                           if c in slot_rev}
                engine.segments.append(Segment(
                    content=tuple(range(base, base + length)),
                    seq=int(arrays["ins_seq"][i]),
                    client=slot_rev.get(int(arrays["ins_client"][i])),
                    removed_seq=None if rem == none_seq else rem,
                    removed_client=slot_rev.get(
                        int(arrays["rem_client"][i])),
                    removed_overlap=overlap,
                ))
            return vec

        cells: dict[tuple[int, int], Any] = {}
        used = np.asarray(s.cell_used[row.row])
        cell_rh = np.asarray(s.cell_rh[row.row])
        cell_ch = np.asarray(s.cell_ch[row.row])
        cell_val = np.asarray(s.cell_val[row.row])
        for c in range(used.shape[0]):
            if used[c]:
                cells[(int(cell_rh[c]), int(cell_ch[c]))] = \
                    self._val_rev[int(cell_val[c])]
        return (seed_vec(s.rows, row.next_row_handle),
                seed_vec(s.cols, row.next_col_handle), cells)

    def _route_matrix_to_scalar(self, row: _MatrixRow) -> None:
        """Client-slot bitmask exhausted: seed scalar permutation vectors
        + the LWW cell dict from the device row, replay the unapplied
        tail, and serve host-side from now on."""
        if self._matrix_state is None:
            from ..dds.matrix import PermutationVector
            row.scalar = (PermutationVector(None), PermutationVector(None),
                          {})
        else:
            row.scalar = self._seed_matrix_scalar(row)
        self._pending_ops -= len(row.pending)
        row.pending = []
        for op, seq, ref_seq, client in row.raw_log:
            self._matrix_scalar_apply(row, op, seq, ref_seq, client)
        row.raw_log = []  # the scalar vectors ARE the state from here on
        if self._matrix_state is not None:
            self._matrix_state = self._blank_matrix_device_row(row.row)
        self.stats["overflow_routed"] += 1
        self._export_stats()

    def _matrix_scalar_apply(self, row: _MatrixRow, op: dict, seq: int,
                             ref_seq: int, client: str) -> None:
        rows_vec, cols_vec, cells = row.scalar
        target = op["target"]
        if target in ("rows", "cols"):
            (rows_vec if target == "rows" else cols_vec).apply_remote(
                op, seq, ref_seq, client)
        else:
            rh = rows_vec.handle_at(op["row"], ref_seq, client)
            ch = cols_vec.handle_at(op["col"], ref_seq, client)
            if rh is not None and ch is not None:
                cells[(rh, ch)] = op["value"]

    def _blank_matrix_device_row(self, row: int) -> mxk.MatrixState:
        s = self._matrix_state

        def blank_merge(ms: mtk.MergeState) -> mtk.MergeState:
            return mtk.MergeState(**{
                f: (getattr(ms, f).at[row].set(_MERGE_FILL[f])
                    if f != "prop_val" else ms.prop_val.at[row].set(0))
                for f in mtk.MergeState._fields})

        return s._replace(
            rows=blank_merge(s.rows), cols=blank_merge(s.cols),
            cell_used=s.cell_used.at[row].set(False),
            cell_count=s.cell_count.at[row].set(0))

    def _ensure_matrix_state(self) -> None:
        if self._matrix_state is None:
            self._matrix_state = mxk.init_state(
                self._matrix_capacity, self._matrix_vec_slots,
                self._matrix_cell_slots, self._matrix_overlap_words)

    def _grow_matrix_overlap(self, need_words: int) -> None:
        """Widen the remover-bitmask planes of both permutation vectors
        (32 more writer slots per word) — matrix twin of the merge pools'
        grow_overlap."""
        new = _next_pow2_width(self._matrix_overlap_words, need_words)
        if new == self._matrix_overlap_words:
            return
        extra = new - self._matrix_overlap_words
        if self._matrix_state is not None:
            def pad_ov(ms: mtk.MergeState) -> mtk.MergeState:
                return ms._replace(rem_overlap=jnp.asarray(
                    _pad_axis(ms.rem_overlap, 2, extra, 0)))
            self._matrix_state = self._matrix_state._replace(
                rows=pad_ov(self._matrix_state.rows),
                cols=pad_ov(self._matrix_state.cols))
        self._matrix_overlap_words = new

    def _grow_matrix_rows(self) -> None:
        old = self._matrix_capacity
        self._matrix_capacity = old * 2
        if self._matrix_state is not None:
            self._matrix_state = jax.device_put(
                self._pad_matrix_state(self._matrix_state, rows_extra=old))

    @staticmethod
    def _pad_matrix_state(s: mxk.MatrixState, rows_extra: int = 0,
                          vec_extra: int = 0,
                          cell_extra: int = 0) -> mxk.MatrixState:
        def pad_merge(ms: mtk.MergeState) -> mtk.MergeState:
            out = {}
            for f in mtk.MergeState._fields:
                a = _pad_axis(getattr(ms, f), 0, rows_extra, _MERGE_FILL[f])
                if f != "count" and vec_extra:
                    a = _pad_axis(a, 1, vec_extra, _MERGE_FILL[f])
                out[f] = a
            return mtk.MergeState(**out)

        cell_fill = dict(cell_rh=-1, cell_ch=-1, cell_val=0, cell_seq=0,
                         cell_used=False)
        cells = {}
        for f, fill in cell_fill.items():
            a = _pad_axis(getattr(s, f), 0, rows_extra, fill)
            if cell_extra:
                a = _pad_axis(a, 1, cell_extra, fill)
            cells[f] = a
        return mxk.MatrixState(
            rows=pad_merge(s.rows), cols=pad_merge(s.cols),
            cell_count=_pad_axis(s.cell_count, 0, rows_extra, 0), **cells)

    def _matrix_vec_shortfall(self, rows: list[_MatrixRow]
                              ) -> tuple[int, int]:
        """(vec_extra, cell_extra) pow2 growth needed for the dirty rows
        (each vector op can consume 2 slots; each cell op 1 cell slot)."""
        margins = mxk.capacity_margin(self._matrix_state)
        vec_extra = cell_extra = 0
        for r in rows:
            vec_need = 2 * len(r.pending) + 2
            cell_need = len(r.pending) + 1
            worst_vec = min(int(margins["rows"][r.row]),
                            int(margins["cols"][r.row]))
            if vec_need > worst_vec:
                vec_extra = max(vec_extra,
                                _next_pow2(vec_need - worst_vec))
            cell_margin = int(margins["cells"][r.row])
            if cell_need > cell_margin:
                cell_extra = max(cell_extra,
                                 _next_pow2(cell_need - cell_margin))
        return vec_extra, cell_extra

    def _flush_matrix(self) -> None:
        rows = [r for r in self._matrix_rows.values() if r.pending]
        if not rows:
            return
        self._ensure_matrix_state()
        vec_extra, cell_extra = self._matrix_vec_shortfall(rows)
        if cell_extra:
            # Dedup the cell append log before paying for growth on ANY
            # path — after cell-run storms it is mostly superseded
            # duplicates (the per-op fallback would otherwise ratchet
            # device memory that one compaction frees).
            self._matrix_state = mxk.compact_cell_log(self._matrix_state)
            self.stats["compactions"] += 1
            vec_extra, cell_extra = self._matrix_vec_shortfall(rows)
        if vec_extra:
            # Zamboni the permutation vectors before paying for growth —
            # tombstoned row/col segments below the window pack away.
            min_seq = np.full(self._matrix_capacity, -1, np.int32)
            for r in self._matrix_rows.values():
                min_seq[r.row] = r.min_seq
            ms = jnp.asarray(min_seq)
            self._matrix_state = self._matrix_state._replace(
                rows=mtk.compact(self._matrix_state.rows, ms),
                cols=mtk.compact(self._matrix_state.cols, ms))
            self.stats["compactions"] += 1
            vec_extra, cell_extra = self._matrix_vec_shortfall(rows)
        if vec_extra or cell_extra:
            self._matrix_state = jax.device_put(self._pad_matrix_state(
                self._matrix_state, vec_extra=vec_extra,
                cell_extra=cell_extra))
            self._matrix_vec_slots += vec_extra
            self._matrix_cell_slots += cell_extra
        k = _tick_k(max(len(r.pending) for r in rows))
        # Config-4 fast path: a flush that is ALL cell writes whose refs
        # cover every structural op applies scan-free as one [B, k] tile
        # (apply_cell_run) — the steady state of a settled grid under
        # concurrent writers. Any vector op in flight falls back to the
        # exact per-op/step path.
        if all(op["target"] == mxk.MX_CELL
               and op["ref_seq"] >= r.last_vec_seq
               for r in rows for op in r.pending):
            counts = np.asarray(self._matrix_state.cell_count)
            deficit = k + 1 - (self._matrix_cell_slots - int(counts.max()))
            if deficit > 0:
                # Dedup the append log (superseded writes pack away)
                # before paying for a bigger table — the cell analog of
                # the vector zamboni above.
                self._matrix_state = mxk.compact_cell_log(
                    self._matrix_state)
                self.stats["compactions"] += 1
                counts = np.asarray(self._matrix_state.cell_count)
                deficit = k + 1 - (self._matrix_cell_slots
                                   - int(counts.max()))
            if deficit > 0:
                extra = _next_pow2(deficit)
                self._matrix_state = jax.device_put(self._pad_matrix_state(
                    self._matrix_state, vec_extra=0, cell_extra=extra))
                self._matrix_cell_slots += extra
            cells_per_doc: list[list[dict]] = [
                [] for _ in range(self._matrix_capacity)]
            refs = np.zeros(self._matrix_capacity, np.int32)
            clients = np.zeros(self._matrix_capacity, np.int32)
            for r in rows:
                cells_per_doc[r.row] = r.pending
                refs[r.row] = min(op["ref_seq"] for op in r.pending)
            run = mxk.make_cell_run_batch(
                cells_per_doc, self._matrix_capacity, k, refs, clients)
            self._matrix_state = mxk.apply_cell_run(self._matrix_state, run)
            self.stats["cell_run_ticks"] = (
                self.stats.get("cell_run_ticks", 0) + 1)
        else:
            per_doc = [[] for _ in range(self._matrix_capacity)]
            for r in rows:
                per_doc[r.row] = r.pending
            batch = mxk.make_matrix_op_batch(per_doc,
                                             self._matrix_capacity, k)
            self._matrix_state = mxp.apply_tick_best(self._matrix_state,
                                                     batch)
        self.stats["device_ops"] += sum(len(r.pending) for r in rows)
        self.stats["flushes"] += 1
        for r in rows:
            r.pending = []
            r.raw_log = []  # device row now reflects the whole history
            r.applied_seq = r.last_seq
            r.applied_min_seq = r.min_seq

    # -- tree channels (SharedTree.ts:446 behind the service) ------------------
    #
    # Device-served edit shapes (everything else routes the channel to the
    # scalar fallback, which replays the exact sequenced-edit log through
    # Transaction — always correct, never fast):
    #
    #   [set_value]                      → TREE_SET_VALUE
    #   [detach(single-node, no dest)]   → TREE_DETACH
    #   [constraint]                     → TREE_CONSTRAINT_EXISTS (no mutation)
    #   [build, insert(source=build)]    → TREE_INSERT* chain
    #   [detach(single, dest), insert]   → TREE_MOVE* (fused subtree move)
    #
    # Atomicity argument (a scalar Transaction drops the WHOLE edit when
    # any change fails): single-change edits are trivially atomic; a
    # build+insert chain cascades — children/siblings anchor on the
    # previous insert's node, so a failed first placement starves every
    # later op of its anchor; a move pair is one device op. Multi-change
    # edits outside these shapes (e.g. two independent set_values) cannot
    # cascade, so they are not device-served.

    def _tree_row(self, key: ChannelKey) -> _TreeRow:
        state = self._tree_rows.get(key)
        if state is None:
            row = len(self._tree_rows)
            if row >= self._tree_capacity:
                self._grow_tree_rows()
            state = _TreeRow(row)
            self._tree_rows[key] = state
        return state

    def _ensure_tree_state(self) -> None:
        if self._tree_state is None:
            self._tree_state = tk.init_state(self._tree_capacity,
                                             self._tree_slots)

    def _grow_tree_rows(self) -> None:
        old = self._tree_capacity
        self._tree_capacity = old * 2
        if self._tree_state is not None:
            fills = dict(exists=False, parent=-1, trait=0, rank=0, payload=0)
            padded = {f: _pad_axis(getattr(self._tree_state, f), 0, old,
                                   fills[f])
                      for f in tk.TreeState._fields}
            # Fresh rows must carry a live root in slot 0.
            padded["exists"][old:, 0] = True
            self._tree_state = jax.device_put(tk.TreeState(**padded))

    def _grow_tree_slots(self, need: int) -> None:
        new = _next_pow2_width(self._tree_slots, need)
        if new == self._tree_slots:
            return
        extra = new - self._tree_slots
        if self._tree_state is not None:
            fills = dict(exists=False, parent=-1, trait=0, rank=0, payload=0)
            self._tree_state = jax.device_put(tk.TreeState(**{
                f: _pad_axis(getattr(self._tree_state, f), 1, extra,
                             fills[f])
                for f in tk.TreeState._fields}))
        self._tree_slots = new

    def _blank_tree_row(self, row: int) -> tk.TreeState:
        s = self._tree_state
        return tk.TreeState(
            exists=s.exists.at[row].set(False).at[row, 0].set(True),
            parent=s.parent.at[row].set(-1),
            trait=s.trait.at[row].set(0),
            rank=s.rank.at[row].set(0),
            payload=s.payload.at[row].set(0))

    def _ingest_tree(self, key: ChannelKey, channel_op: dict,
                     message: SequencedDocumentMessage) -> None:
        row = self._tree_row(key)
        seq = message.sequence_number
        if seq <= row.last_seq:
            return  # bus replay
        row.last_seq = seq
        edit = channel_op["edit"]
        if row.scalar is not None:
            self._tree_scalar_apply(row, edit)
            self.stats["scalar_ops"] += 1
            return
        row.raw_log.append(edit)
        ops = self._encode_tree_edit(row, edit)
        if row.scalar is not None:
            # A capacity flush inside encoding overflowed this row and the
            # scalar replay (from raw_log) already covered this edit.
            return
        if ops is None:
            self._route_tree_to_scalar(row)
            self.stats["scalar_ops"] += 1
            return
        row.pending.extend(ops)
        self._pending_ops += len(ops)

    def _tree_scalar_apply(self, row: _TreeRow, edit: dict) -> None:
        txn = Transaction(row.scalar)
        if txn.apply_edit(edit) == VALID:
            row.scalar = txn.snapshot

    def _route_tree_to_scalar(self, row: _TreeRow) -> None:
        """Replay the channel's sequenced edits (on top of the trimmed
        base snapshot, if any) through the scalar Transaction path and
        serve it host-side from now on."""
        snap = (TreeSnapshot.load(row.base) if row.base is not None
                else TreeSnapshot())
        for edit in row.raw_log:
            txn = Transaction(snap)
            if txn.apply_edit(edit) == VALID:
                snap = txn.snapshot
        row.scalar = snap
        row.raw_log = []  # the snapshot IS the state from here on
        self._pending_ops -= len(row.pending)
        row.pending = []
        if self._tree_state is not None:
            self._tree_state = self._blank_tree_row(row.row)
        self.stats["overflow_routed"] += 1
        self._export_stats()

    # -- tree edit translation -------------------------------------------------

    def _tree_trait_id(self, row: _TreeRow, label: Any) -> int:
        tid = row.trait_ids.get(label)
        if tid is None:
            tid = len(row.trait_rev) + 1  # 0 = the root's own trait plane
            row.trait_ids[label] = tid
            row.trait_rev.append(label)
        return tid

    def _encode_tree_edit(self, row: _TreeRow,
                          edit: dict) -> list[dict] | None:
        """Device ops for one edit; [] = no state change either way
        (scalar-invalid or no-op), None = unsupported shape → scalar."""
        changes = edit.get("changes")
        if not isinstance(changes, list):
            return None
        if len(changes) == 1:
            ch = changes[0]
            kind = ch.get("type")
            if kind == "set_value":
                slot = row.slot_of.get(ch.get("node"))
                if slot is None:
                    return []  # unknown node: scalar-invalid
                return [dict(kind=tk.TREE_SET_VALUE, node=slot,
                             payload=self._intern(ch.get("payload")))]
            if kind == "detach" and ch.get("destination") is None:
                return self._encode_tree_detach(row, ch.get("source"))
            if kind == "constraint":
                return self._encode_tree_constraint(row, ch)
            return None
        if len(changes) == 2:
            first, second = changes
            if (first.get("type") == "build"
                    and second.get("type") == "insert"
                    and second.get("source") == first.get("destination")):
                return self._encode_tree_build_insert(row, first, second)
            if (first.get("type") == "detach"
                    and first.get("destination") is not None
                    and second.get("type") == "insert"
                    and second.get("source") == first.get("destination")):
                return self._encode_tree_move(row, first, second)
        return None

    @staticmethod
    def _single_node_range(source: Any) -> tuple[str, bool] | None:
        """(sibling id, is_real_range) for a same-sibling range; None for
        ranges the device cannot enumerate (multi-node / trait-based).
        is_real_range is False for empty or inverted ranges — scalar
        treats those as a valid no-op / an invalid edit respectively, and
        either way no state changes."""
        if not isinstance(source, dict):
            return None
        start, end = source.get("start"), source.get("end")
        if not (isinstance(start, dict) and isinstance(end, dict)):
            return None
        sib = start.get("referenceSibling")
        if sib is None or end.get("referenceSibling") != sib:
            return None
        real = (start.get("side") == "before"
                and end.get("side") == "after")
        return sib, real

    def _encode_tree_detach(self, row: _TreeRow,
                            source: Any) -> list[dict] | None:
        rng = self._single_node_range(source)
        if rng is None:
            return None
        sib, real = rng
        if not real or sib == ROOT_ID:
            return []
        slot = row.slot_of.get(sib)
        if slot is None:
            return []  # unknown anchor: scalar-invalid
        return [dict(kind=tk.TREE_DETACH, node=slot)]

    def _encode_tree_constraint(self, row: _TreeRow,
                                ch: dict) -> list[dict]:
        # Constraints never mutate; their only effect is edit validity,
        # which for a single-change edit changes no state. Emit EXISTS
        # checks where translatable so the device path is exercised.
        rng = ch.get("range")
        if not isinstance(rng, dict):
            return []
        ops = []
        for place in (rng.get("start"), rng.get("end")):
            if not isinstance(place, dict):
                continue
            sib = place.get("referenceSibling")
            if sib and sib != ROOT_ID:
                slot = row.slot_of.get(sib)
                if slot:
                    ops.append(dict(kind=tk.TREE_CONSTRAINT_EXISTS,
                                    node=slot))
        return ops

    _TREE_INVALID = "invalid"

    def _encode_tree_place(self, row: _TreeRow, place: Any):
        """(insert kind, anchor slot, trait id) | "invalid" (scalar drops
        the edit — no state change) | None (unsupported)."""
        if not isinstance(place, dict):
            return None
        if "referenceSibling" in place:
            sib = place["referenceSibling"]
            if sib == ROOT_ID:
                return self._TREE_INVALID
            slot = row.slot_of.get(sib)
            if slot is None:
                return self._TREE_INVALID
            kind = (tk.TREE_INSERT_BEFORE if place.get("side") == "before"
                    else tk.TREE_INSERT_AFTER)
            return kind, slot, 0
        trait = place.get("referenceTrait")
        if not isinstance(trait, dict):
            return None
        pslot = row.slot_of.get(trait.get("parent"))
        if pslot is None:
            return self._TREE_INVALID
        tid = self._tree_trait_id(row, trait.get("label"))
        kind = (tk.TREE_INSERT_START if place.get("side") == "start"
                else tk.TREE_INSERT)
        return kind, pslot, tid

    @staticmethod
    def _count_spec_nodes(specs: list) -> int | None:
        total = 0
        stack = list(specs)
        while stack:
            spec = stack.pop()
            if not isinstance(spec, dict) or "id" not in spec:
                return None
            total += 1
            for child_specs in (spec.get("traits") or {}).values():
                stack.extend(child_specs)
        return total

    def _ensure_tree_slots(self, row: _TreeRow, fresh: int) -> None:
        shortfall = fresh - len(row.free)
        if shortfall <= 0 or row.next_slot + shortfall <= self._tree_slots:
            return
        # Apply pending first so the exists read-back is current, then
        # reclaim slots of deleted/never-materialized nodes (the tree
        # zamboni); grow only if that is not enough. NOTE: the flush can
        # overflow-route THIS row to scalar — callers re-check.
        self.flush()
        if row.scalar is None:
            self._reclaim_tree_slots(row)
        shortfall = fresh - len(row.free)
        if shortfall > 0 and row.next_slot + shortfall > self._tree_slots:
            self._grow_tree_slots(_next_pow2(row.next_slot + shortfall))

    def _reclaim_tree_slots(self, row: _TreeRow) -> None:
        if self._tree_state is None:
            return
        exists = np.asarray(self._tree_state.exists[row.row])
        in_free = set(row.free)
        for slot in list(row.info_of):
            if slot != 0 and slot not in in_free and not exists[slot]:
                node_id, _ = row.info_of.pop(slot)
                row.slot_of.pop(node_id, None)
                row.free.append(slot)
        self.stats["compactions"] += 1

    def _alloc_tree_slot(self, row: _TreeRow, spec: dict) -> int:
        slot = row.free.pop() if row.free else row.next_slot
        if slot == row.next_slot:
            row.next_slot += 1
        row.slot_of[spec["id"]] = slot
        row.info_of[slot] = (spec["id"], spec.get("definition", ""))
        return slot

    def _encode_tree_build_insert(self, row: _TreeRow, build: dict,
                                  insert: dict) -> list[dict] | None:
        specs = build.get("source")
        if not isinstance(specs, list) or not specs:
            return None
        count = self._count_spec_nodes(specs)
        if count is None:
            return None
        # Conservative: an id collision with ANY known node (alive or not)
        # breaks the cascade-atomicity argument (a colliding insert fails
        # but leaves an EXISTING anchor) — scalar handles it exactly.
        stack = list(specs)
        while stack:
            spec = stack.pop()
            if spec["id"] in row.slot_of:
                return None
            for child_specs in (spec.get("traits") or {}).values():
                stack.extend(child_specs)
        place = self._encode_tree_place(row, insert.get("destination"))
        if place is None:
            return None
        if place == self._TREE_INVALID:
            return []
        self._ensure_tree_slots(row, count)
        if row.scalar is not None:
            return []  # flush inside ensure overflow-routed this row
        kind, anchor, tid = place
        ops: list[dict] = []
        prev_slot = -1
        for spec in specs:
            slot = self._alloc_tree_slot(row, spec)
            if prev_slot < 0:
                ops.append(dict(kind=kind, node=slot, parent=anchor,
                                trait=tid,
                                payload=self._intern(spec.get("payload"))))
            else:
                # Later top-level siblings chain after the previous one,
                # matching the scalar's list splice order.
                ops.append(dict(kind=tk.TREE_INSERT_AFTER, node=slot,
                                parent=prev_slot,
                                payload=self._intern(spec.get("payload"))))
            prev_slot = slot
            self._encode_tree_children(row, spec, slot, ops)
        return ops

    def _encode_tree_children(self, row: _TreeRow, spec: dict,
                              parent_slot: int, ops: list[dict]) -> None:
        for label, child_specs in (spec.get("traits") or {}).items():
            tid = self._tree_trait_id(row, label)
            for child in child_specs:
                slot = self._alloc_tree_slot(row, child)
                ops.append(dict(kind=tk.TREE_INSERT, node=slot,
                                parent=parent_slot, trait=tid,
                                payload=self._intern(child.get("payload"))))
                self._encode_tree_children(row, child, slot, ops)

    _MOVE_KIND = {tk.TREE_INSERT: tk.TREE_MOVE,
                  tk.TREE_INSERT_START: tk.TREE_MOVE_START,
                  tk.TREE_INSERT_BEFORE: tk.TREE_MOVE_BEFORE,
                  tk.TREE_INSERT_AFTER: tk.TREE_MOVE_AFTER}

    def _encode_tree_move(self, row: _TreeRow, detach: dict,
                          insert: dict) -> list[dict] | None:
        rng = self._single_node_range(detach.get("source"))
        if rng is None:
            return None
        sib, real = rng
        if not real or sib == ROOT_ID:
            return []  # empty/inverted range: no-op or invalid either way
        slot = row.slot_of.get(sib)
        if slot is None:
            return []  # unknown node: scalar-invalid
        place = self._encode_tree_place(row, insert.get("destination"))
        if place is None:
            return None
        if place == self._TREE_INVALID:
            return []
        kind, anchor, tid = place
        return [dict(kind=self._MOVE_KIND[kind], node=slot, parent=anchor,
                     trait=tid)]

    def _flush_tree(self) -> None:
        items = [(key, r) for key, r in self._tree_rows.items()
                 if r.pending]
        if not items:
            return
        self._ensure_tree_state()
        k = _tick_k(max(len(r.pending) for _, r in items))
        per_doc: list[list[dict]] = [[] for _ in range(self._tree_capacity)]
        for _, r in items:
            per_doc[r.row] = r.pending
        batch = tk.make_tree_op_batch(per_doc, self._tree_capacity, k)
        self._tree_state, outs = tk.apply_tick(self._tree_state, batch)
        overflowed = np.asarray(jnp.any(outs.overflow, axis=1))
        self.stats["device_ops"] += sum(len(r.pending) for _, r in items)
        self.stats["flushes"] += 1
        for _, r in items:
            r.pending = []
        for key, r in items:
            if overflowed[r.row]:
                # Rank space exhausted mid-tick: the device state is
                # partially applied; rebuild exactly from base + edit log.
                self._route_tree_to_scalar(r)
            elif len(r.raw_log) > _TREE_LOG_TRIM:
                # Clean boundary: the device row reflects the whole log —
                # fold it into a materialized base snapshot.
                r.base = self.tree_snapshot(*key)
                r.raw_log = []
                self.stats["compactions"] += 1

    def _ingest_map(self, key: ChannelKey, channel_op: dict,
                    message: SequencedDocumentMessage) -> None:
        row = self._map_row(key)
        if row.literal_values:
            raise ValueError(
                f"channel {key} is storm-served (literal values); dict-path "
                "ops cannot mix on one channel")
        seq = message.sequence_number
        if seq <= row.last_seq:
            return
        row.last_seq = seq
        kind = channel_op["type"]
        if kind == "clear":
            row.pending.append(dict(kind=mk.MAP_CLEAR, seq=seq))
        else:
            slot = row.key_slots.setdefault(channel_op["key"],
                                            len(row.key_slots))
            if kind == "set":
                row.pending.append(dict(
                    kind=mk.MAP_SET, slot=slot, seq=seq,
                    value=self._intern(channel_op["value"])))
            else:
                row.pending.append(dict(kind=mk.MAP_DELETE, slot=slot,
                                        seq=seq))
        self._pending_ops += 1

    # -- flush (the device tick) ----------------------------------------------

    def scalar_fraction(self) -> float:
        """Fraction of served channel ops that ran on the scalar fallback
        instead of the device kernels — the silent-degradation signal
        (VERDICT r3 weak #6). 0.0 = everything device-served."""
        total = self.stats["device_ops"] + self.stats["scalar_ops"]
        return self.stats["scalar_ops"] / total if total else 0.0

    def _export_stats(self) -> None:
        """Mirror the routing counters into the shared metrics registry so
        alfred's get_metrics / tools/monitor.py surface the scalar-path
        fraction of serving traffic, not just kernel throughput."""
        for name, value in self.stats.items():
            self.metrics.gauge(f"merge_host.{name}").set(value)
        self.metrics.gauge("merge_host.scalar_fraction").set(
            self.scalar_fraction())

    def flush(self) -> None:
        """Apply every pending op: at most one ``apply_tick`` per kernel."""
        import time as _time
        self.metrics.gauge("merge_host.queue_depth").set(self._pending_ops)
        start = _time.perf_counter()
        self._readmit_scalar_rows()
        # Mega tier adaptation BEFORE the merge tick: a row promoted
        # here serves this very flush from the sequence-parallel pool
        # (pending ops ride the move).
        self.maybe_adapt_megadocs()
        self._flush_merge()
        self._flush_map()
        self._flush_matrix()
        self._flush_tree()
        if self._pending_ops:
            self.metrics.histogram("merge_host.tick_seconds").observe(
                _time.perf_counter() - start)
            self.metrics.counter("merge_host.merged_ops").inc(
                self._pending_ops)
        self._export_stats()
        self._pending_ops = 0

    def autotune_block_geometry(self, min_observations: int = 8,
                                fire_threshold: float = 0.5,
                                head_fraction: float | None = None
                                ) -> dict:
        """Per-bucket (NB, Bk) retune from OBSERVED op locality: a block
        pool whose pre_tick rebalance trigger fired on >=
        ``fire_threshold`` of its flush gates is serving a
        head-concentrated stream — its hot block refills every tick, so
        trade NB for a larger Bk (same total slots; capacity contracts
        unchanged) and the hot block absorbs several ticks per spill.
        Resize geometry, not replay frequency (ADVICE item 4). Call it
        off the hot path (maintenance cadence); the re-block itself goes
        through the packed-flat seam and is replay-deterministic.
        ``head_fraction`` overrides the per-pool observed rate with an
        explicit concentration estimate (the parallel of
        ShardedServing.retune_text_geometry's argument — an operator or
        an out-of-band placement plane can force a known shape).
        Returns {bucket_slots: (nb, bk)} for the pools it re-blocked."""
        retuned: dict[int, tuple[int, int]] = {}
        for slots, pool in sorted(self._merge_pools.items()):
            if not isinstance(pool, _BlockMergePool):
                continue
            if pool.pre_ticks < min_observations:
                continue
            rate = (pool.fire_rate() if head_fraction is None
                    else head_fraction)
            if rate < fire_threshold:
                continue
            # Target: the hot block absorbs 1..4 ticks (at the pow2
            # tick-width floor of 32 ops, 2 slots each) before the
            # trigger re-fires — the SAME Bk-scaling rule as
            # choose_block_geometry, under the pool constraint
            # nb * bk == slots (pools whose slot count the pow2 Bk does
            # not divide are skipped, not crashed — __init__ tolerates
            # such shapes).
            bk = min(mtb.bk_for_locality(32, rate), pool.slots)
            if bk <= pool.bk or pool.slots % bk:
                continue
            pool.retune(bk)
            self.stats["geometry_retunes"] += 1
            self.metrics.counter("merge.geometry_retunes").inc()
            retuned[slots] = (pool.nb, pool.bk)
        return retuned

    def _readmit_scalar_rows(self) -> None:
        """The reverse of the overflow escape (VERDICT r2 weak #7 — the
        all-or-nothing exit): a scalar-served merge channel whose writer
        set shrank back under the device client bitmask (zamboni
        collected the departed writers' segments as the window advanced)
        re-encodes onto a device row and is device-served again."""
        for key, row in self._merge_rows.items():
            if row.scalar is None:
                continue
            if row.min_seq <= row.readmit_seen_min:
                continue  # window unmoved since the last failed attempt
            if not self._try_readmit_merge(key, row):
                row.readmit_seen_min = row.min_seq

    def _try_readmit_merge(self, key: ChannelKey, row: _MergeRow) -> bool:
        engine = row.scalar
        clients: set[str] = set()
        for seg in engine.segments:
            if seg.length == 0:
                continue
            if seg.client is not None:
                clients.add(seg.client)
            if seg.removed_client is not None:
                clients.add(seg.removed_client)
            clients.update(seg.removed_overlap)
        # Hysteresis: readmit only with headroom below the ceiling, or a
        # single fresh writer would bounce the channel straight back out.
        if len(clients) > self.max_client_slots - 4:
            return False
        segments = [s for s in engine.segments if s.length > 0]
        slot_of = {c: i for i, c in enumerate(sorted(clients))}
        pool = self._pool_for(max(len(segments) * 2, self._merge_slots))
        if clients:
            pool.grow_overlap(mtk.overlap_words_for(len(clients)))
        row.pool = None
        pool.alloc(row)
        key_slots: dict[str, int] = {}
        for seg in segments:
            for prop_key in (seg.props or {}):
                key_slots.setdefault(prop_key, len(key_slots))
        if len(key_slots) > pool.num_props:
            pool.grow_props(len(key_slots))

        s = pool.slots
        extra_axis = {"prop_val": pool.num_props,
                      "rem_overlap": pool.overlap_words}
        arrays = {f: np.full(
            (s, extra_axis[f]) if f in extra_axis else (s,),
            _MERGE_FILL[f],
            np.bool_ if f == "valid" else np.int32)
            for f in mtk.MergeState._fields if f != "count"}
        pool.text.chunks[row.row] = []
        pool.text.used[row.row] = 0
        for i, seg in enumerate(segments):
            arrays["valid"][i] = True
            arrays["length"][i] = seg.length
            arrays["ins_seq"][i] = max(seg.seq, 0)  # baseline loads are 0
            arrays["ins_client"][i] = slot_of.get(seg.client, -1)
            if seg.removed_seq is not None:
                arrays["rem_seq"][i] = seg.removed_seq
                arrays["rem_client"][i] = slot_of.get(seg.removed_client, -1)
                for overlap_client in seg.removed_overlap:
                    _set_overlap_bit(arrays["rem_overlap"][i],
                                     slot_of[overlap_client])
            if isinstance(seg.content, str):
                text = seg.content
            else:  # Marker or handle/placeholder run
                text = _MARKER_CHAR * seg.length
            arrays["pool_start"][i] = pool.text.append(row.row, text)
            for prop_key, value in (seg.props or {}).items():
                arrays["prop_val"][i, key_slots[prop_key]] = \
                    self._intern(value)
        state_arrays = dict(arrays)
        state_arrays["count"] = np.int32(len(segments))
        pool.write_row(row.row, state_arrays)
        row.client_slots = slot_of
        row.key_slots = key_slots
        row.scalar = None
        row.raw_log = []
        row.pending = []
        row.applied_seq = row.last_seq
        row.applied_min_seq = row.min_seq
        self.stats["readmissions"] += 1
        return True

    def _flush_merge(self) -> None:
        rows = [r for r in self._merge_rows.values() if r.pending]
        if not rows:
            return
        # Capacity: each op can consume up to 2 fresh slots (split+place /
        # split+split). Compact rows under pressure; rows that STILL don't
        # fit migrate to the next bucket — only they pay for the growth.
        for _ in range(32):  # bounded: each pass doubles the short rows
            short_rows: list[tuple[_MergeRow, int]] = []
            for pool, pool_rows in self._rows_by_pool(rows).items():
                margins = pool.margins()
                need = np.zeros(pool.capacity, np.int64)
                for r in pool_rows:
                    need[r.row] = 2 * len(r.pending) + 2
                short = need > margins
                if not short.any():
                    continue
                min_seq = np.full(pool.capacity, -1, np.int32)
                for r in pool.members:
                    if r is not None and short[r.row]:
                        min_seq[r.row] = r.min_seq
                pool.state = pool.compact_state(jnp.asarray(min_seq))
                self.stats["compactions"] += 1
                still = need > pool.margins()
                if still.any():
                    # Second chance before paying for a bigger bucket:
                    # repack the short rows' text pools so live document
                    # order is pool-contiguous, then COALESCE adjacent
                    # acked runs (device zamboni pack) — a long-lived
                    # document's slot need is its collab window, not its
                    # history.
                    for r in pool_rows:
                        if still[r.row]:
                            self._repack_text_pool(r)
                    pool.state = pool.compact_state(jnp.asarray(min_seq),
                                                    coalesce=True)
                    self.stats["compactions"] += 1
                    still = need > pool.margins()
                for r in pool_rows:
                    if still[r.row]:
                        short_rows.append((r, int(need[r.row])))
            if not short_rows:
                break
            for r, n in short_rows:
                live = int(np.asarray(r.pool.state.count[r.row]))
                self._migrate_merge_row(
                    r, max(_next_pow2(live + n), r.pool.slots * 2))

        # One apply_tick per dirty bucket; prop planes grow per pool.
        for pool, pool_rows in self._rows_by_pool(rows).items():
            max_props = max(len(r.key_slots) for r in pool_rows)
            if max_props > pool.num_props:
                pool.grow_props(max_props)
            k = _tick_k(max(len(r.pending) for r in pool_rows))
            need = np.zeros(pool.capacity, np.int64)
            for r in pool_rows:
                need[r.row] = 2 * len(r.pending) + 2
            if pool.pre_tick(need):
                self.stats["rebalances"] += 1
                self.metrics.counter("merge.rebalance_fires").inc()
            per_doc = [[] for _ in range(pool.capacity)]
            for r in pool_rows:
                per_doc[r.row] = r.pending
            batch = mtk.make_merge_op_batch(per_doc, pool.capacity, k,
                                            pool.client_capacity)
            pool.state = pool.apply(batch)
            if isinstance(pool, _ShardedMergePool):
                # Sequence-parallel attribution: ops served across the
                # mesh, and the boundary-exchange bound — each op's
                # split/place moves at most 2 one-hop ppermute edge
                # exchanges (ShardPrims.roll; merge_apply_vec shifts by
                # <= 2), the "ring step" cost the monitor renders.
                n_ops = sum(len(r.pending) for r in pool_rows)
                self.metrics.counter("megadoc.sharded_ops").inc(n_ops)
                self.metrics.counter(
                    "megadoc.boundary_exchanges").inc(2 * n_ops)
            overflow = pool.take_overflow()
            if overflow is not None:
                for r in pool_rows:
                    idx = int(overflow[r.row])
                    if idx != int(mtb.OVF_NONE):
                        # Block full mid-tick: the device froze the row
                        # at op ``idx``; replay the tail exactly through
                        # the flat kernel and re-block. A replay that
                        # FAILS quarantines only this channel (scalar
                        # route) — one poisoned doc must never abort the
                        # whole bucket's flush.
                        src_pool, src_row = r.pool, r.row
                        try:
                            self._replay_block_overflow(r, r.pending[idx:])
                        except Exception as err:
                            if r.pool is not src_pool or r.row != src_row:
                                # Died mid-migration: the half-written
                                # destination row is abandoned; the
                                # frozen source row is still intact.
                                r.pool.release(r.row)
                                r.pool, r.row = src_pool, src_row
                                src_pool.members[src_row] = r
                            self._quarantine_merge_row(
                                r, r.pending[idx:], err)
            self.stats["device_ops"] += sum(
                len(r.pending) for r in pool_rows)
            for r in pool_rows:
                if r.pool is None:
                    continue  # quarantined above; already settled
                r.pending = []
                # The device row now reflects everything in raw_log; the
                # tail resets so host memory per channel stays bounded.
                r.raw_log = []
                r.applied_seq = r.last_seq
                r.applied_min_seq = r.min_seq
                if r.pool.text.used[r.row] > r.repack_at:
                    self._repack_text_pool(r)
        self.stats["flushes"] += 1

    def _replay_block_overflow(self, row: _MergeRow,
                               rest: list[dict]) -> None:
        """A block filled mid-tick: the device froze the row before op
        ``rest[0]``. Pack the frozen table into a flat row, replay the
        tail through the flat kernel (same semantics, pinned by the
        differential fuzz), and re-block — migrating to a bigger bucket
        when the replayed table outgrows this one."""
        pool = row.pool
        arrays = pool.row_arrays(row.row)
        order = np.flatnonzero(arrays["valid"])
        n = len(order)
        slots = _next_pow2(max(8, n + 2 * len(rest) + 2))
        packed: dict[str, Any] = {}
        for f in mtk.MergeState._fields:
            if f == "count":
                continue
            src = np.asarray(arrays[f])
            dst = np.full((slots,) + src.shape[1:], _MERGE_FILL[f],
                          np.bool_ if f == "valid" else np.int32)
            dst[:n] = src[order]
            packed[f] = jnp.asarray(dst)[None]
        state1 = mtk.MergeState(count=jnp.asarray([n], np.int32),
                                **packed)
        batch = mtk.make_merge_op_batch([rest], 1, _tick_k(len(rest)))
        state1 = mtk.apply_tick(state1, batch)
        out = {f: np.asarray(getattr(state1, f)[0])
               for f in mtk.MergeState._fields}
        if slots > pool.slots:
            src_pool, src_row = pool, row.row
            dst_pool = self._pool_for(slots)
            if dst_pool.num_props < src_pool.num_props:
                dst_pool.grow_props(src_pool.num_props)
            if dst_pool.overlap_words < src_pool.overlap_words:
                dst_pool.grow_overlap(src_pool.overlap_words)
            out["prop_val"] = _pad_axis(
                out["prop_val"], 1,
                dst_pool.num_props - out["prop_val"].shape[1], 0)
            out["rem_overlap"] = _pad_axis(
                out["rem_overlap"], 1,
                dst_pool.overlap_words - out["rem_overlap"].shape[1], 0)
            # slots is pow2 > pool.slots >= the smallest bucket, so the
            # destination bucket is exactly slots wide — no slot-axis
            # re-padding (block write_row re-blocks from any flat width
            # anyway; a flat dst would only appear via seg_mesh pools,
            # which start at sharded_slot_threshold >= slots here).
            assert dst_pool.slots == slots or isinstance(
                dst_pool, _BlockMergePool), (dst_pool.slots, slots)
            dst_pool.alloc(row)
            dst_pool.write_row(row.row, out)
            dst_pool.text.chunks[row.row] = src_pool.text.chunks[src_row]
            dst_pool.text.used[row.row] = src_pool.text.used[src_row]
            src_pool.release(src_row)
            self.stats["migrations"] += 1
        else:
            pool.write_row(row.row, out)
        self.stats["block_overflow_replays"] += 1

    def _decode_pending_op(self, row: _MergeRow, enc: dict,
                           slot_rev: dict[int, str],
                           key_rev: dict[int, str]
                           ) -> tuple[dict, int, int, str | None]:
        """Invert :meth:`_ingest_merge`'s encoding of one pending op back
        to a (channel_op, seq, ref_seq, client) tuple the scalar engine
        applies — the quarantine path's exact-tail replay input. Marker/
        item runs reconstruct exactly as :meth:`_seed_merge_engine` does
        (position-space-exact placeholders). The caller builds the
        reverse maps once per row, not once per op."""
        client = slot_rev.get(enc["client"])
        if enc["kind"] == mtk.MT_INSERT:
            start = enc["pool_start"]
            text = row.pool.text.buffer(row.row)[
                start:start + enc["text_len"]]
            op: dict[str, Any] = {"type": "insert", "pos": enc["pos"]}
            if text and text == _MARKER_CHAR * len(text):
                if len(text) == 1:
                    op["marker"] = {"ref_type": "simple", "id": None}
                else:
                    op["items"] = [None] * len(text)
            else:
                op["text"] = text
        elif enc["kind"] == mtk.MT_REMOVE:
            op = {"type": "remove", "start": enc["pos"], "end": enc["end"]}
        else:  # MT_ANNOTATE — one encoded op per (key, value)
            op = {"type": "annotate", "start": enc["pos"],
                  "end": enc["end"],
                  "props": {key_rev[enc["prop_key"]]:
                            self._val_rev[enc["prop_val"]]}}
        return op, enc["seq"], enc["ref_seq"], client

    def _quarantine_merge_row(self, row: _MergeRow, rest: list[dict],
                              err: Exception) -> None:
        """The generalized per-doc escape hatch (ISSUE 5 tentpole): ANY
        per-row tick failure — overflow replay included — seeds the
        scalar engine from the frozen last-good device table, replays the
        unapplied tail through it, and serves the channel scalar from
        here on; the rest of the batch never sees the failure. The
        channel readmits to the device through the existing
        :meth:`_readmit_scalar_rows` path once its window compacts."""
        self.metrics.counter("merge_host.quarantines").inc()
        engine = self._seed_merge_engine(row)
        slot_rev = {s: c for c, s in row.client_slots.items()}
        key_rev = {s: k for k, s in row.key_slots.items()}
        for enc in rest:
            op, seq, ref_seq, client = self._decode_pending_op(
                row, enc, slot_rev, key_rev)
            engine.apply_remote(op, seq, ref_seq, client)
        engine.update_min_seq(row.min_seq)
        self.stats["quarantined_channels"] += 1
        self._demote_row_to_scalar(row, engine)

    def _repack_text_pool(self, row: _MergeRow) -> None:
        """Zamboni for text bytes: the pool is append-only, so a long-lived
        document's pool grows with total INSERTED text. Rebuild it from the
        slices the live table still references (tombstones included) and
        rewrite the row's pool_start plane. The table's slices land in
        TABLE order, so after this pass adjacent document-order segments
        are pool-contiguous — the precondition for the coalescing zamboni
        (mergetree_kernel.compact coalesce).

        Pending (not-yet-applied) insert ops also hold pool offsets; the
        pressure path repacks BEFORE the tick, so their slices migrate
        too and their op dicts are rewritten in place."""
        pool = row.pool
        arrays = pool.row_arrays(row.row)
        buffer = pool.text.buffer(row.row)
        starts = arrays["pool_start"].copy()
        pieces: list[str] = []
        used = 0
        for i in range(arrays["valid"].shape[0]):
            if not arrays["valid"][i] or arrays["length"][i] == 0:
                continue
            start = int(starts[i])
            length = int(arrays["length"][i])
            pieces.append(buffer[start:start + length])
            starts[i] = used
            used += length
        for op in row.pending:
            if op["kind"] == mtk.MT_INSERT and op["text_len"] > 0:
                start = op["pool_start"]
                pieces.append(buffer[start:start + op["text_len"]])
                op["pool_start"] = used
                used += op["text_len"]
        pool.set_pool_start(row.row, starts)
        pool.text.chunks[row.row] = pieces
        pool.text.used[row.row] = used
        # Back off if the row is legitimately large: retry only after
        # another threshold's worth of churn.
        row.repack_at = max(_TEXT_REPACK_MIN, 3 * used)
        self.stats["compactions"] += 1

    @staticmethod
    def _rows_by_pool(rows: list[_MergeRow]
                      ) -> dict[_MergePool, list[_MergeRow]]:
        grouped: dict[_MergePool, list[_MergeRow]] = {}
        for r in rows:
            if r.pending and r.pool is not None:
                grouped.setdefault(r.pool, []).append(r)
        return grouped

    def _flush_map(self) -> None:
        rows = [r for r in self._map_rows.values() if r.pending]
        if not rows:
            return
        max_keys = max(len(r.key_slots) for r in rows)
        if max_keys > self._map_slots:
            self._grow_map_slots(max_keys)
        k = _tick_k(max(len(r.pending) for r in rows))
        per_doc = [[] for _ in range(self._map_capacity)]
        for r in rows:
            per_doc[r.row] = r.pending
        batch = mk.make_map_op_batch(per_doc, self._map_capacity, k)
        self._xstate = mk.apply_tick(self._xstate, batch)
        self.stats["device_ops"] += sum(len(r.pending) for r in rows)
        self.stats["flushes"] += 1
        for r in rows:
            r.pending = []

    # -- materialization -------------------------------------------------------

    def channels(self, doc_id: str) -> list[ChannelKey]:
        return sorted(
            [k for k in self._merge_rows if k.doc_id == doc_id]
            + [k for k in self._map_rows if k.doc_id == doc_id]
            + [k for k in self._matrix_rows if k.doc_id == doc_id]
            + [k for k in self._tree_rows if k.doc_id == doc_id])

    def tree_snapshot(self, doc_id: str, datastore: str,
                      channel: str) -> dict:
        """Converged tree of a SharedTree channel in the canonical
        ``TreeSnapshot.serialize()`` form (byte-comparable to replicas)."""
        key = ChannelKey(doc_id, datastore, channel)
        row = self._tree_rows[key]
        if row.pending:
            self.flush()
        if row.scalar is not None:
            return row.scalar.serialize()
        if self._tree_state is None:
            return TreeSnapshot().serialize()
        exists = np.asarray(self._tree_state.exists[row.row])
        parent = np.asarray(self._tree_state.parent[row.row])
        trait = np.asarray(self._tree_state.trait[row.row])
        rank = np.asarray(self._tree_state.rank[row.row])
        payload = np.asarray(self._tree_state.payload[row.row])
        # Children of each (parent, trait), rank-ascending (slot index
        # breaks exact-rank ties — ranks are unique per trait in practice:
        # colliding midpoints overflow to the scalar path instead).
        by_parent: dict[int, dict[int, list[int]]] = {}
        for slot in range(exists.shape[0]):
            if exists[slot] and slot != 0:
                by_parent.setdefault(int(parent[slot]), {}).setdefault(
                    int(trait[slot]), []).append(slot)
        out: dict[str, dict] = {}
        for slot in range(exists.shape[0]):
            if not exists[slot]:
                continue
            node_id, definition = row.info_of[slot]
            traits = {}
            for tid, slots in sorted(
                    by_parent.get(slot, {}).items(),
                    key=lambda kv: row.trait_rev[kv[0] - 1]):
                slots.sort(key=lambda i: (int(rank[i]), i))
                traits[row.trait_rev[tid - 1]] = [
                    row.info_of[i][0] for i in slots]
            out[node_id] = {
                "definition": definition,
                "payload": self._val_rev[payload[slot]],
                "traits": traits,
                "parent": (None if slot == 0 else
                           [row.info_of[int(parent[slot])][0],
                            row.trait_rev[int(trait[slot]) - 1]]),
            }
        return dict(sorted(out.items()))

    def matrix_grid(self, doc_id: str, datastore: str,
                    channel: str) -> list[list]:
        """Converged dense grid of a matrix channel (None = unset)."""
        key = ChannelKey(doc_id, datastore, channel)
        row = self._matrix_rows[key]
        if row.pending:
            self.flush()
        if row.scalar is not None:
            rows_vec, cols_vec, cells = row.scalar
            row_handles = [h for seg in rows_vec.engine.segments
                           if seg.removed_seq is None
                           for h in seg.content]
            col_handles = [h for seg in cols_vec.engine.segments
                           if seg.removed_seq is None
                           for h in seg.content]
            return [[cells.get((r, c)) for c in col_handles]
                    for r in row_handles]
        grid = mxk.materialize_grid(self._matrix_state, row.row,
                                    self._val_rev)
        return grid

    def text(self, doc_id: str, datastore: str, channel: str) -> str:
        """Converged text of a string channel (markers stripped)."""
        key = ChannelKey(doc_id, datastore, channel)
        row = self._merge_rows[key]
        if row.pending:
            self.flush()
        if row.scalar is not None:
            return "".join(
                seg.content for seg in row.scalar.segments
                if seg.removed_seq is None and not seg.is_marker
                and isinstance(seg.content, str))
        text = row.pool.materialize_row(row.row)
        return text.replace(_MARKER_CHAR, "")

    def rich_text(self, doc_id: str, datastore: str,
                  channel: str) -> list[tuple[str, dict | None]]:
        """(text, props) runs of a string channel, markers as ("\\x00", …) —
        the device-state analog of walking live segments."""
        key = ChannelKey(doc_id, datastore, channel)
        row = self._merge_rows[key]
        if row.pending:
            self.flush()
        if row.scalar is not None:
            return [(seg.content if isinstance(seg.content, str)
                     else _MARKER_CHAR,
                     dict(seg.props) if seg.props else None)
                    for seg in row.scalar.segments
                    if seg.removed_seq is None and seg.length > 0]
        key_rev = {slot: name for name, slot in row.key_slots.items()}
        arrays = row.pool.row_arrays(row.row)
        valid = arrays["valid"]
        length = arrays["length"]
        rem = arrays["rem_seq"]
        start = arrays["pool_start"]
        pvals = arrays["prop_val"]
        buffer = row.pool.text.buffer(row.row)
        out = []
        for i in range(valid.shape[0]):
            if not (valid[i] and rem[i] == mtk.NONE_SEQ and length[i] > 0):
                continue
            props = {key_rev[p]: self._val_rev[pvals[i, p]]
                     for p in range(pvals.shape[1])
                     if pvals[i, p] != 0 and p in key_rev}
            out.append((buffer[start[i]:start[i] + length[i]],
                        props or None))
        return out

    def map_entries(self, doc_id: str, datastore: str,
                    channel: str) -> dict[str, Any]:
        """Converged entries of a map channel (wire-format values)."""
        key = ChannelKey(doc_id, datastore, channel)
        row = self._map_rows[key]
        if row.pending:
            self.flush()
        present = np.asarray(self._xstate.present[row.row])
        value = np.asarray(self._xstate.value[row.row])
        if row.literal_values:
            return {name: int(value[slot])
                    for name, slot in row.key_slots.items()
                    if present[slot]}
        return {name: self._val_rev[value[slot]]
                for name, slot in row.key_slots.items() if present[slot]}

    def summarize(self, doc_id: str) -> dict:
        """Materialize every tracked channel of a document from device state
        (the summary the scribe would upload for the server-side replica)."""
        self.flush()
        datastores: dict[str, dict] = {}
        for key in self.channels(doc_id):
            channels = datastores.setdefault(key.datastore, {})
            if key in self._merge_rows:
                channels[key.channel] = {
                    "kind": "mergeTree",
                    "content": self.rich_text(*key),
                }
            elif key in self._matrix_rows:
                channels[key.channel] = {
                    "kind": "matrix",
                    "grid": self.matrix_grid(*key),
                }
            elif key in self._tree_rows:
                channels[key.channel] = {
                    "kind": "tree",
                    "tree": self.tree_snapshot(*key),
                }
            else:
                channels[key.channel] = {
                    "kind": "map",
                    "entries": self.map_entries(*key),
                }
        seqs = [r.last_seq for k, r in self._merge_rows.items()
                if k.doc_id == doc_id]
        seqs += [r.last_seq for k, r in self._map_rows.items()
                 if k.doc_id == doc_id]
        seqs += [r.last_seq for k, r in self._matrix_rows.items()
                 if k.doc_id == doc_id]
        seqs += [r.last_seq for k, r in self._tree_rows.items()
                 if k.doc_id == doc_id]
        return {"datastores": datastores,
                "sequence_number": max(seqs, default=0)}

    # -- snapshot / restore (device-pool checkpoint) ---------------------------
    #
    # The crash-consistency leg (ISSUE 4): the device pools are volatile,
    # so a serving-host restart either replays the WHOLE durable op log
    # (exact, O(history)) or restores a periodic host-side checkpoint and
    # replays only the tail. export_state() captures every device plane
    # (merge pools, map state, matrix state) plus the host-side string/
    # slot mappings the kernels cannot carry, in a wire-serializable
    # form (GitSnapshotStore uploads it as chunked content-addressed
    # blobs). import_state() rebuilds a FRESH host byte-identically —
    # block pools re-install their exact [B, NB, Bk] planes.
    #
    # Scope: merge rows (device AND scalar-routed), the map state, and
    # matrix rows (device and scalar). Tree channels are NOT snapshotted:
    # they rebuild from the scriptorium durable-log replay (the merger
    # lambda already does this on restart); export records their keys so
    # the caller knows replay is required.

    def export_state(self) -> dict:
        """Wire-serializable checkpoint of all device pools + host maps.
        Flushes first so no pending/raw tails need serializing."""
        self.flush()
        pools = []
        pool_index: dict[int, int] = {}
        all_pools = ([(False, s, p) for s, p
                      in sorted(self._merge_pools.items())]
                     + [(True, s, p) for s, p
                        in sorted(self._mega_pools.items())])
        for mega, slots, pool in all_pools:
            kind = ("sharded" if isinstance(pool, _ShardedMergePool)
                    else "block" if isinstance(pool, _BlockMergePool)
                    else "flat")
            pool_index[id(pool)] = len(pools)
            pools.append({
                "kind": kind, "mega": mega, "slots": pool.slots,
                "num_props": pool.num_props,
                "overlap_words": pool.overlap_words,
                "capacity": pool.capacity,
                # Block pools carry their (possibly autotuned) geometry
                # so import re-blocks identically — the retune must
                # survive the snapshot/restore seam byte-for-byte.
                **({"block_geometry": [pool.nb, pool.bk]}
                   if kind == "block" else {}),
                "planes": {f: _nd_pack(np.asarray(getattr(pool.state, f)))
                           for f in type(pool.state)._fields},
                "text": [pool.text.buffer(r) for r in range(pool.capacity)],
                "text_used": list(pool.text.used),
                "free": list(pool.free),
                "n_members": len(pool.members),
            })
        merge_rows = []
        for key, r in self._merge_rows.items():
            assert not r.pending and not r.raw_log, (
                "export_state after flush() found pending ops")
            merge_rows.append({
                "key": list(key),
                "pool": (pool_index[id(r.pool)]
                         if r.pool is not None else None),
                "row": r.row,
                "client_slots": r.client_slots,
                "key_slots": r.key_slots,
                "min_seq": r.min_seq, "last_seq": r.last_seq,
                "applied_seq": r.applied_seq,
                "applied_min_seq": r.applied_min_seq,
                "repack_at": r.repack_at,
                "scalar": (_dump_engine(r.scalar)
                           if r.scalar is not None else None),
            })
        map_rows = [{
            "key": list(key), "row": r.row, "key_slots": r.key_slots,
            "last_seq": r.last_seq, "literal": r.literal_values,
        } for key, r in self._map_rows.items()]
        matrix = None
        if self._matrix_rows or self._matrix_state is not None:
            state = None
            if self._matrix_state is not None:
                s = self._matrix_state
                state = {f: _nd_pack(np.asarray(getattr(s, f)))
                         if f not in ("rows", "cols") else
                         {g: _nd_pack(np.asarray(getattr(getattr(s, f), g)))
                          for g in mtk.MergeState._fields}
                         for f in mxk.MatrixState._fields}
            matrix = {
                "capacity": self._matrix_capacity,
                "vec_slots": self._matrix_vec_slots,
                "cell_slots": self._matrix_cell_slots,
                "overlap_words": self._matrix_overlap_words,
                "state": state,
                "rows": [{
                    "key": list(key), "row": r.row,
                    "client_slots": r.client_slots,
                    "last_seq": r.last_seq, "min_seq": r.min_seq,
                    "applied_seq": r.applied_seq,
                    "applied_min_seq": r.applied_min_seq,
                    "next_row_handle": r.next_row_handle,
                    "next_col_handle": r.next_col_handle,
                    "last_vec_seq": r.last_vec_seq,
                    "scalar": (_dump_matrix_scalar(r.scalar)
                               if r.scalar is not None else None),
                } for key, r in self._matrix_rows.items()],
            }
        return {
            "version": 1,
            "vals": list(self._val_rev),
            "merge_pools": pools,
            "merge_rows": merge_rows,
            "map": {
                "capacity": self._map_capacity, "slots": self._map_slots,
                "planes": {f: _nd_pack(np.asarray(getattr(self._xstate, f)))
                           for f in mk.MapState._fields},
                "rows": map_rows,
            },
            "matrix": matrix,
            # Not snapshotted — these channels need a durable-log replay.
            "tree_keys": [list(k) for k in self._tree_rows],
            "stats": dict(self.stats),
        }

    def import_state(self, snap: dict) -> None:
        """Rebuild a FRESH host from :meth:`export_state` output."""
        assert not (self._merge_rows or self._map_rows or self._matrix_rows
                    or self._tree_rows), "import_state needs a fresh host"
        if snap.get("version") != 1:
            raise ValueError(f"unknown snapshot version {snap.get('version')}")
        self._val_rev = list(snap["vals"])
        self._vals = {repr(v): i for i, v in enumerate(self._val_rev)
                      if i != 0}

        pools: list[_MergePool] = []
        for p in snap["merge_pools"]:
            if p["kind"] == "block":
                # Pre-geometry snapshots (no "block_geometry") carry the
                # lane-width default; autotuned ones re-block exactly.
                geom = p.get("block_geometry")
                pool: _MergePool = _BlockMergePool(
                    p["slots"], p["num_props"], p["capacity"],
                    p["overlap_words"],
                    block_slots=geom[1] if geom else None)
            elif p["kind"] == "flat":
                pool = _MergePool(p["slots"], p["num_props"], p["capacity"],
                                  p["overlap_words"])
            else:  # sharded: needs the mesh the exporting host had
                if self.seg_mesh is None:
                    raise ValueError(
                        "snapshot holds a sequence-parallel pool but this "
                        "host has no seg_mesh")
                pool = _ShardedMergePool(p["slots"], p["num_props"],
                                         self.seg_mesh, p["capacity"],
                                         p["overlap_words"],
                                         mega=p.get("mega", False))
            cls = type(pool.state)
            pool.state = pool.place(jax.device_put(cls(
                **{f: _nd_unpack(p["planes"][f]) for f in cls._fields})))
            pool.text = mtk.TextPool(p["capacity"])
            for r, text in enumerate(p["text"]):
                if text:
                    pool.text.chunks[r] = [text]
            pool.text.used = list(p["text_used"])
            pool.free = list(p["free"])
            pool.members = [None] * p["n_members"]
            if p.get("mega", False):
                self._mega_pools[p["slots"]] = pool
            else:
                self._merge_pools[p["slots"]] = pool
            pools.append(pool)

        for rec in snap["merge_rows"]:
            r = _MergeRow()
            r.client_slots = dict(rec["client_slots"])
            r.key_slots = dict(rec["key_slots"])
            r.min_seq, r.last_seq = rec["min_seq"], rec["last_seq"]
            r.applied_seq = rec["applied_seq"]
            r.applied_min_seq = rec["applied_min_seq"]
            r.repack_at = rec["repack_at"]
            if rec["scalar"] is not None:
                r.scalar = _load_engine(rec["scalar"])
                r.pool, r.row = None, -1
            else:
                r.pool = pools[rec["pool"]]
                r.row = rec["row"]
                r.pool.members[r.row] = r
            self._merge_rows[ChannelKey(*rec["key"])] = r

        m = snap["map"]
        self._map_capacity, self._map_slots = m["capacity"], m["slots"]
        self._xstate = jax.device_put(mk.MapState(
            **{f: _nd_unpack(m["planes"][f]) for f in mk.MapState._fields}))
        for rec in m["rows"]:
            row = _MapRow(rec["row"])
            row.key_slots = dict(rec["key_slots"])
            row.last_seq = rec["last_seq"]
            row.literal_values = rec["literal"]
            self._map_rows[ChannelKey(*rec["key"])] = row
        # Row allocator resumes past the restored rows; gaps left by
        # pre-snapshot evictions are reissued exactly like live frees.
        used = {r.row for r in self._map_rows.values()}
        self._map_row_count = max(used, default=-1) + 1
        self._free_map_rows = [r for r in range(self._map_row_count)
                               if r not in used]

        mx = snap.get("matrix")
        if mx is not None:
            self._matrix_capacity = mx["capacity"]
            self._matrix_vec_slots = mx["vec_slots"]
            self._matrix_cell_slots = mx["cell_slots"]
            self._matrix_overlap_words = mx["overlap_words"]
            if mx["state"] is not None:
                st = mx["state"]
                self._matrix_state = jax.device_put(mxk.MatrixState(**{
                    f: (mtk.MergeState(**{g: _nd_unpack(st[f][g])
                                          for g in mtk.MergeState._fields})
                        if f in ("rows", "cols") else _nd_unpack(st[f]))
                    for f in mxk.MatrixState._fields}))
            for rec in mx["rows"]:
                row = _MatrixRow(rec["row"])
                row.client_slots = dict(rec["client_slots"])
                row.last_seq, row.min_seq = rec["last_seq"], rec["min_seq"]
                row.applied_seq = rec["applied_seq"]
                row.applied_min_seq = rec["applied_min_seq"]
                row.next_row_handle = rec["next_row_handle"]
                row.next_col_handle = rec["next_col_handle"]
                row.last_vec_seq = rec["last_vec_seq"]
                if rec["scalar"] is not None:
                    row.scalar = _load_matrix_scalar(rec["scalar"])
                self._matrix_rows[ChannelKey(*rec["key"])] = row


def _nd_pack(a: np.ndarray) -> dict:
    """ndarray → wire dict (dtype + shape + b64 of the raw bytes)."""
    import base64
    a = np.ascontiguousarray(a)
    return {"d": a.dtype.str, "s": list(a.shape),
            "b": base64.b64encode(a.tobytes()).decode()}


def _nd_unpack(d: dict) -> np.ndarray:
    import base64
    return np.frombuffer(base64.b64decode(d["b"]),
                         np.dtype(d["d"])).reshape(d["s"]).copy()


def _dump_content(content) -> Any:
    if isinstance(content, str):
        return content
    if isinstance(content, Marker):
        return {"marker": [content.ref_type, content.id]}
    return {"items": list(content)}  # handle / item run


def _load_content(data) -> Any:
    if isinstance(data, str):
        return data
    if "marker" in data:
        return Marker(ref_type=data["marker"][0], id=data["marker"][1])
    return tuple(data["items"])


def _dump_engine(engine: MergeEngine) -> dict:
    """Serialize a server-side scalar engine (no local pending state —
    server engines apply remote ops only, so groups/local_seq are empty)."""
    return {
        "current_seq": engine.current_seq,
        "min_seq": engine.min_seq,
        "segments": [{
            "content": _dump_content(seg.content),
            "seq": seg.seq,
            "client": seg.client,
            "removed_seq": seg.removed_seq,
            "removed_client": seg.removed_client,
            "removed_overlap": sorted(seg.removed_overlap),
            "props": seg.props,
        } for seg in engine.segments],
    }


def _load_engine(data: dict) -> MergeEngine:
    engine = MergeEngine(local_client=None)
    engine.current_seq = data["current_seq"]
    engine.min_seq = data["min_seq"]
    for s in data["segments"]:
        engine.segments.append(Segment(
            content=_load_content(s["content"]),
            seq=s["seq"], client=s["client"],
            removed_seq=s["removed_seq"],
            removed_client=s["removed_client"],
            removed_overlap=set(s["removed_overlap"]),
            props=dict(s["props"]) if s["props"] else None,
        ))
    return engine


def _dump_matrix_scalar(scalar: tuple) -> dict:
    rows_vec, cols_vec, cells = scalar
    return {
        "rows": {"engine": _dump_engine(rows_vec.engine),
                 "next_handle": rows_vec.next_handle},
        "cols": {"engine": _dump_engine(cols_vec.engine),
                 "next_handle": cols_vec.next_handle},
        "cells": [[rh, ch, v] for (rh, ch), v in sorted(cells.items())],
    }


def _load_matrix_scalar(data: dict) -> tuple:
    from ..dds.matrix import PermutationVector

    def load_vec(d):
        vec = PermutationVector(None)
        vec.engine = _load_engine(d["engine"])
        vec.next_handle = d["next_handle"]
        return vec

    return (load_vec(data["rows"]), load_vec(data["cols"]),
            {(rh, ch): v for rh, ch, v in data["cells"]})


__all__ = ["KernelMergeHost", "ChannelKey"]
