"""KernelMergeHost — device-resident converged document state on the server.

Reference parity: the *server-observed* hot loops of the reference — the
merge-tree sequenced apply path (packages/dds/merge-tree/src/mergeTree.ts:
1974 insertingWalk, 2626 markRangeRemoved, 2584 annotateRange) and the
SharedMap message fold (packages/dds/map/src/mapKernel.ts:510
tryProcessMessage) — hosted *behind the service seams* as one batched
device program, per SURVEY.md §7 / BASELINE.json: every (document,
channel) is a row of :class:`~fluidframework_tpu.ops.mergetree_kernel.
MergeState` or :class:`~fluidframework_tpu.ops.map_kernel.MapState`; a
service tick applies the pending sequenced ops of *all* channels in one
``apply_tick`` call (vmap over the row axis — the workload's data-parallel
axis, shardable over the device mesh via
:func:`fluidframework_tpu.parallel.mesh.shard_state`).

The host owns what the kernels cannot:

* string→int mappings (client id → slot lane, property key → key slot,
  value → interned id, text → pool offsets);
* capacity management — before each flush it checks
  :func:`~fluidframework_tpu.ops.mergetree_kernel.capacity_margin`,
  runs the device zamboni (:func:`~fluidframework_tpu.ops.
  mergetree_kernel.compact`) on rows under pressure, and grows the slot
  axes (doubling) when compaction is not enough;
* overflow routing — a channel that exceeds the device client-slot
  bitmask (``MAX_CLIENT_SLOTS``) is re-routed to the scalar
  :class:`~fluidframework_tpu.dds.mergetree.MergeEngine` by replaying its
  full op log (the "route over-capacity documents to the scalar path"
  contract from ``capacity_margin``'s docstring);
* summaries — converged channel contents materialized from device state.

Wire in: feed every sequenced message via :meth:`ingest` (LocalCollabServer
does this from its broadcast path; RouterliciousService via the merger
lambda in routerlicious.py). Ops buffer host-side and hit the device in
ticks — either when ``pending_ops`` crosses ``flush_threshold`` or when a
reader forces :meth:`flush`.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..dds.mergetree import MergeEngine
from ..ops import map_kernel as mk
from ..ops import mergetree_kernel as mtk
from ..protocol.messages import MessageType, SequencedDocumentMessage
from .kernel_host import _next_pow2

_MERGE_OPS = frozenset({"insert", "remove", "annotate", "group"})
_MAP_OPS = frozenset({"set", "delete", "clear"})

# A marker occupies one pool char; stripped at materialization. Real text
# never contains NUL (the wire format is JSON-ish strings).
_MARKER_CHAR = "\x00"


class ChannelKey(NamedTuple):
    doc_id: str
    datastore: str
    channel: str


class _MergeRow:
    __slots__ = ("row", "client_slots", "key_slots", "pending", "raw_log",
                 "scalar", "min_seq", "last_seq", "markers")

    def __init__(self, row: int) -> None:
        self.row = row
        self.client_slots: dict[str, int] = {}
        self.key_slots: dict[str, int] = {}
        self.pending: list[dict] = []
        # Full sequenced history (subop, seq, ref_seq, client) — the replay
        # source if this channel overflows to the scalar path.
        self.raw_log: list[tuple[dict, int, int, str]] = []
        self.scalar: MergeEngine | None = None
        self.min_seq = 0
        self.last_seq = 0
        self.markers = 0


class _MapRow:
    __slots__ = ("row", "key_slots", "pending", "last_seq")

    def __init__(self, row: int) -> None:
        self.row = row
        self.key_slots: dict[str, int] = {}
        self.pending: list[dict] = []
        self.last_seq = 0


def _pad_axis(a, axis: int, extra: int, fill):
    a = np.asarray(a)
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, extra)
    return np.pad(a, widths, constant_values=fill)


_MERGE_FILL = dict(valid=False, length=0, ins_seq=0, ins_client=-1,
                   rem_seq=int(mtk.NONE_SEQ), rem_client=-1, rem_overlap=0,
                   pool_start=0, prop_val=0, count=0)
_MAP_FILL = dict(present=False, value=0, vseq=-1, cleared_seq=-1)


class KernelMergeHost:
    """Batched device host for the merge-tree and map apply kernels."""

    def __init__(self, merge_slots: int = 128, map_slots: int = 32,
                 num_props: int = 4, row_capacity: int = 8,
                 flush_threshold: int = 256, metrics=None) -> None:
        from ..utils import MetricsRegistry
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self._merge_capacity = max(1, row_capacity)
        self._map_capacity = max(1, row_capacity)
        self._merge_slots = max(8, merge_slots)
        self._map_slots = max(4, map_slots)
        self._num_props = max(1, num_props)
        self.flush_threshold = flush_threshold

        self._mstate = mtk.init_state(self._merge_capacity, self._merge_slots,
                                      self._num_props)
        self._xstate = mk.init_state(self._map_capacity, self._map_slots)
        self._pool = mtk.TextPool(self._merge_capacity)

        self._merge_rows: dict[ChannelKey, _MergeRow] = {}
        self._map_rows: dict[ChannelKey, _MapRow] = {}
        # Shared value interning (map values + annotate values). Id 0 is
        # reserved for "absent"/None; ids index _val_rev.
        self._vals: dict[str, int] = {}
        self._val_rev: list[Any] = [None]
        self._pending_ops = 0
        # Counters surfaced by the telemetry layer (ops served by the
        # device path vs routed to the scalar fallback).
        self.stats = {"device_ops": 0, "scalar_ops": 0, "flushes": 0,
                      "compactions": 0, "overflow_routed": 0}

    # -- interning -------------------------------------------------------------

    def _intern(self, value: Any) -> int:
        if value is None:
            return 0
        key = repr(value)
        vid = self._vals.get(key)
        if vid is None:
            vid = len(self._val_rev)
            self._vals[key] = vid
            self._val_rev.append(value)
        return vid

    # -- row allocation / growth -----------------------------------------------

    def _merge_row(self, key: ChannelKey) -> _MergeRow:
        state = self._merge_rows.get(key)
        if state is None:
            row = len(self._merge_rows)
            if row >= self._merge_capacity:
                self._grow_merge_rows()
            state = _MergeRow(row)
            self._merge_rows[key] = state
        return state

    def _map_row(self, key: ChannelKey) -> _MapRow:
        state = self._map_rows.get(key)
        if state is None:
            row = len(self._map_rows)
            if row >= self._map_capacity:
                self._grow_map_rows()
            state = _MapRow(row)
            self._map_rows[key] = state
        return state

    def _grow_merge_rows(self) -> None:
        old = self._merge_capacity
        self._merge_capacity = old * 2
        self._mstate = jax.device_put(mtk.MergeState(**{
            f: _pad_axis(getattr(self._mstate, f), 0, old, _MERGE_FILL[f])
            for f in mtk.MergeState._fields}))
        self._pool.chunks += [[] for _ in range(old)]
        self._pool.used += [0] * old

    def _grow_map_rows(self) -> None:
        old = self._map_capacity
        self._map_capacity = old * 2
        self._xstate = jax.device_put(mk.MapState(**{
            f: _pad_axis(getattr(self._xstate, f), 0, old, _MAP_FILL[f])
            for f in mk.MapState._fields}))

    def _grow_merge_slots(self, need: int) -> None:
        new = self._merge_slots
        while new < need:
            new *= 2
        extra = new - self._merge_slots
        self._mstate = jax.device_put(mtk.MergeState(**{
            f: (_pad_axis(getattr(self._mstate, f), 1, extra, _MERGE_FILL[f])
                if f != "count" else np.asarray(self._mstate.count))
            for f in mtk.MergeState._fields}))
        self._merge_slots = new

    def _grow_props(self, need: int) -> None:
        new = self._num_props
        while new < need:
            new *= 2
        extra = new - self._num_props
        self._mstate = self._mstate._replace(prop_val=jnp.asarray(
            _pad_axis(self._mstate.prop_val, 2, extra, 0)))
        self._num_props = new

    def _grow_map_slots(self, need: int) -> None:
        new = self._map_slots
        while new < need:
            new *= 2
        extra = new - self._map_slots
        self._xstate = jax.device_put(mk.MapState(**{
            f: (_pad_axis(getattr(self._xstate, f), 1, extra, _MAP_FILL[f])
                if f != "cleared_seq" else np.asarray(self._xstate.cleared_seq))
            for f in mk.MapState._fields}))
        self._map_slots = new

    # -- ingest ----------------------------------------------------------------

    def ingest(self, doc_id: str, message: SequencedDocumentMessage) -> None:
        """Feed one sequenced message. Non-channel-ops are ignored; merge and
        map channel ops are routed to their device rows."""
        if message.type != MessageType.OPERATION:
            return
        envelope = message.contents
        if not isinstance(envelope, dict) or "address" not in envelope:
            return
        inner = envelope.get("contents")
        if not isinstance(inner, dict) or "address" not in inner:
            return
        channel_op = inner.get("contents")
        if not isinstance(channel_op, dict) or "type" not in channel_op:
            return
        key = ChannelKey(doc_id, envelope["address"], inner["address"])
        kind = channel_op["type"]
        if kind in _MERGE_OPS:
            self._ingest_merge(key, channel_op, message)
        elif kind in _MAP_OPS:
            self._ingest_map(key, channel_op, message)
        if self._pending_ops >= self.flush_threshold:
            self.flush()

    def _ingest_merge(self, key: ChannelKey, channel_op: dict,
                      message: SequencedDocumentMessage) -> None:
        row = self._merge_row(key)
        seq = message.sequence_number
        if seq <= row.last_seq:
            return  # bus replay
        row.last_seq = seq
        row.min_seq = message.minimum_sequence_number
        ref_seq = message.reference_sequence_number
        client = message.client_id
        subops = (channel_op["ops"] if channel_op["type"] == "group"
                  else [channel_op])
        for op in subops:
            row.raw_log.append((op, seq, ref_seq, client))
        if row.scalar is not None:
            for op in subops:
                row.scalar.apply_remote(op, seq, ref_seq, client)
            self.stats["scalar_ops"] += len(subops)
            return
        if (client not in row.client_slots
                and len(row.client_slots) >= mtk.MAX_CLIENT_SLOTS):
            self._route_to_scalar(key, row)
            self.stats["scalar_ops"] += len(subops)
            return
        slot = row.client_slots.setdefault(client, len(row.client_slots))
        for op in subops:
            base = dict(seq=seq, ref_seq=ref_seq, client=slot)
            if op["type"] == "insert":
                if "text" in op:
                    text = op["text"]
                elif "items" in op:
                    # Item-vector insert (e.g. permutation-vector handles):
                    # one placeholder char per item keeps every later
                    # position-based op resolving against correct visible
                    # lengths; item payloads are opaque to the text plane.
                    text = _MARKER_CHAR * len(op["items"])
                    row.markers += len(op["items"])
                else:
                    text = _MARKER_CHAR
                    row.markers += 1
                enc = dict(base, kind=mtk.MT_INSERT, pos=op["pos"],
                           pool_start=self._pool.append(row.row, text),
                           text_len=len(text))
                row.pending.append(enc)
                self._pending_ops += 1
                # An insert may also carry initial props; they apply to the
                # fresh segment only, which at this seq is exactly the
                # inserted range.
                if op.get("props"):
                    self._encode_annotates(
                        row, base, op["pos"], op["pos"] + len(text),
                        op["props"])
            elif op["type"] == "remove":
                row.pending.append(dict(base, kind=mtk.MT_REMOVE,
                                        pos=op["start"], end=op["end"]))
                self._pending_ops += 1
            else:  # annotate
                self._encode_annotates(row, base, op["start"], op["end"],
                                       op["props"])

    def _encode_annotates(self, row: _MergeRow, base: dict, start: int,
                          end: int, props: dict) -> None:
        for prop_key, value in sorted(props.items()):
            kslot = row.key_slots.setdefault(prop_key, len(row.key_slots))
            row.pending.append(dict(base, kind=mtk.MT_ANNOTATE, pos=start,
                                    end=end, prop_key=kslot,
                                    prop_val=self._intern(value)))
            self._pending_ops += 1

    def _route_to_scalar(self, key: ChannelKey, row: _MergeRow) -> None:
        """Client-slot bitmask exhausted: replay the channel's full history
        through the scalar engine and serve it host-side from now on."""
        engine = MergeEngine(local_client=None)
        for op, seq, ref_seq, client in row.raw_log:
            engine.apply_remote(op, seq, ref_seq, client)
        row.scalar = engine
        self._pending_ops -= len(row.pending)
        row.pending = []
        # Release the abandoned device row: zeroing its valid mask keeps
        # later apply_tick/compact passes from dragging stale segments.
        self._mstate = mtk.MergeState(**{
            f: (getattr(self._mstate, f).at[row.row].set(
                _MERGE_FILL[f]) if f != "prop_val"
                else self._mstate.prop_val.at[row.row].set(0))
            for f in mtk.MergeState._fields})
        self.stats["overflow_routed"] += 1

    def _ingest_map(self, key: ChannelKey, channel_op: dict,
                    message: SequencedDocumentMessage) -> None:
        row = self._map_row(key)
        seq = message.sequence_number
        if seq <= row.last_seq:
            return
        row.last_seq = seq
        kind = channel_op["type"]
        if kind == "clear":
            row.pending.append(dict(kind=mk.MAP_CLEAR, seq=seq))
        else:
            slot = row.key_slots.setdefault(channel_op["key"],
                                            len(row.key_slots))
            if kind == "set":
                row.pending.append(dict(
                    kind=mk.MAP_SET, slot=slot, seq=seq,
                    value=self._intern(channel_op["value"])))
            else:
                row.pending.append(dict(kind=mk.MAP_DELETE, slot=slot,
                                        seq=seq))
        self._pending_ops += 1

    # -- flush (the device tick) ----------------------------------------------

    def flush(self) -> None:
        """Apply every pending op: at most one ``apply_tick`` per kernel."""
        import time as _time
        self.metrics.gauge("merge_host.queue_depth").set(self._pending_ops)
        start = _time.perf_counter()
        self._flush_merge()
        self._flush_map()
        if self._pending_ops:
            self.metrics.histogram("merge_host.tick_seconds").observe(
                _time.perf_counter() - start)
            self.metrics.counter("merge_host.merged_ops").inc(
                self._pending_ops)
        self._pending_ops = 0

    def _flush_merge(self) -> None:
        rows = [r for r in self._merge_rows.values() if r.pending]
        if not rows:
            return
        # Prop-plane growth before batch encode (key slots are global per
        # channel but the plane axis is shared).
        max_props = max((len(r.key_slots) for r in rows), default=0)
        if max_props > self._num_props:
            self._grow_props(max_props)

        # Capacity: each op can consume up to 2 fresh slots (split+place /
        # split+split). Compact rows under pressure; grow if still short.
        margins = mtk.capacity_margin(self._mstate)
        need = np.zeros(self._merge_capacity, np.int64)
        min_seq = np.full(self._merge_capacity, -1, np.int32)
        for r in rows:
            need[r.row] = 2 * len(r.pending) + 2
        short = need > margins
        if short.any():
            for r in self._merge_rows.values():
                if short[r.row]:
                    min_seq[r.row] = r.min_seq
            self._mstate = mtk.compact(self._mstate, jnp.asarray(min_seq))
            self.stats["compactions"] += 1
            margins = mtk.capacity_margin(self._mstate)
            still = need > margins
            if still.any():
                worst = int((need - margins)[still].max())
                self._grow_merge_slots(self._merge_slots + _next_pow2(worst))

        k = _next_pow2(max(len(r.pending) for r in rows))
        per_doc = [[] for _ in range(self._merge_capacity)]
        for r in rows:
            per_doc[r.row] = r.pending
        batch = mtk.make_merge_op_batch(per_doc, self._merge_capacity, k)
        self._mstate = mtk.apply_tick(self._mstate, batch)
        self.stats["device_ops"] += sum(len(r.pending) for r in rows)
        self.stats["flushes"] += 1
        for r in rows:
            r.pending = []

    def _flush_map(self) -> None:
        rows = [r for r in self._map_rows.values() if r.pending]
        if not rows:
            return
        max_keys = max(len(r.key_slots) for r in rows)
        if max_keys > self._map_slots:
            self._grow_map_slots(max_keys)
        k = _next_pow2(max(len(r.pending) for r in rows))
        per_doc = [[] for _ in range(self._map_capacity)]
        for r in rows:
            per_doc[r.row] = r.pending
        batch = mk.make_map_op_batch(per_doc, self._map_capacity, k)
        self._xstate = mk.apply_tick(self._xstate, batch)
        self.stats["device_ops"] += sum(len(r.pending) for r in rows)
        self.stats["flushes"] += 1
        for r in rows:
            r.pending = []

    # -- materialization -------------------------------------------------------

    def channels(self, doc_id: str) -> list[ChannelKey]:
        return sorted(
            [k for k in self._merge_rows if k.doc_id == doc_id]
            + [k for k in self._map_rows if k.doc_id == doc_id])

    def text(self, doc_id: str, datastore: str, channel: str) -> str:
        """Converged text of a string channel (markers stripped)."""
        key = ChannelKey(doc_id, datastore, channel)
        row = self._merge_rows[key]
        if row.pending:
            self.flush()
        if row.scalar is not None:
            return "".join(
                seg.content for seg in row.scalar.segments
                if seg.removed_seq is None and not seg.is_marker
                and isinstance(seg.content, str))
        text = mtk.materialize(self._mstate, self._pool, row.row)
        return text.replace(_MARKER_CHAR, "")

    def rich_text(self, doc_id: str, datastore: str,
                  channel: str) -> list[tuple[str, dict | None]]:
        """(text, props) runs of a string channel, markers as ("\\x00", …) —
        the device-state analog of walking live segments."""
        key = ChannelKey(doc_id, datastore, channel)
        row = self._merge_rows[key]
        if row.pending:
            self.flush()
        if row.scalar is not None:
            return [(seg.content if isinstance(seg.content, str)
                     else _MARKER_CHAR,
                     dict(seg.props) if seg.props else None)
                    for seg in row.scalar.segments
                    if seg.removed_seq is None and seg.length > 0]
        key_rev = {slot: name for name, slot in row.key_slots.items()}
        valid = np.asarray(self._mstate.valid[row.row])
        length = np.asarray(self._mstate.length[row.row])
        rem = np.asarray(self._mstate.rem_seq[row.row])
        start = np.asarray(self._mstate.pool_start[row.row])
        pvals = np.asarray(self._mstate.prop_val[row.row])
        buffer = self._pool.buffer(row.row)
        out = []
        for i in range(valid.shape[0]):
            if not (valid[i] and rem[i] == mtk.NONE_SEQ and length[i] > 0):
                continue
            props = {key_rev[p]: self._val_rev[pvals[i, p]]
                     for p in range(pvals.shape[1])
                     if pvals[i, p] != 0 and p in key_rev}
            out.append((buffer[start[i]:start[i] + length[i]],
                        props or None))
        return out

    def map_entries(self, doc_id: str, datastore: str,
                    channel: str) -> dict[str, Any]:
        """Converged entries of a map channel (wire-format values)."""
        key = ChannelKey(doc_id, datastore, channel)
        row = self._map_rows[key]
        if row.pending:
            self.flush()
        present = np.asarray(self._xstate.present[row.row])
        value = np.asarray(self._xstate.value[row.row])
        return {name: self._val_rev[value[slot]]
                for name, slot in row.key_slots.items() if present[slot]}

    def summarize(self, doc_id: str) -> dict:
        """Materialize every tracked channel of a document from device state
        (the summary the scribe would upload for the server-side replica)."""
        self.flush()
        datastores: dict[str, dict] = {}
        for key in self.channels(doc_id):
            channels = datastores.setdefault(key.datastore, {})
            if key in self._merge_rows:
                channels[key.channel] = {
                    "kind": "mergeTree",
                    "content": self.rich_text(*key),
                }
            else:
                channels[key.channel] = {
                    "kind": "map",
                    "entries": self.map_entries(*key),
                }
        seqs = [r.last_seq for k, r in self._merge_rows.items()
                if k.doc_id == doc_id]
        seqs += [r.last_seq for k, r in self._map_rows.items()
                 if k.doc_id == doc_id]
        return {"datastores": datastores,
                "sequence_number": max(seqs, default=0)}


__all__ = ["KernelMergeHost", "ChannelKey"]
