"""Audience announcements — the service side of the container's audience
roster (container.ts:1700 region): every connection, including read-only
ones that never reach the quorum, is announced via system signals
(``client_id`` None on the wire; clients reject the shape from peers).

Shared by both service assemblies (RouterliciousService and
LocalCollabServer); connection objects are duck-typed
(client_id / mode / on_signal).

Scale note (the viewer-plane round): presence is INTEREST-SAMPLED past
``max_roster`` members — the snapshot a newcomer receives carries a
bounded member sample plus the exact ``total``, and join announcements
to peers stop once the roster is past the bound (peers track the count,
not 100k individual joins). Read-only viewers never enter these
connection maps at all (server/broadcaster.py keeps its own sampled
presence plane); the bound here protects the writer/reader roster from
pathological fan-in on one hot doc.
"""

from __future__ import annotations

AUDIENCE_SIGNAL = "__audience__"

#: Default roster-sample bound for interest-sampled presence: snapshots
#: list at most this many members (plus the exact total); per-join
#: announcements to peers stop past it.
MAX_ROSTER = 256


def _signal(content: dict) -> dict:
    return {"client_id": None, "content": {"type": AUDIENCE_SIGNAL,
                                           **content}}


def roster_sample(connections, limit: int | None = None
                  ) -> tuple[list[dict], int]:
    """(bounded member sample, exact total) over a connection map —
    the interest-sampled presence shape shared with the viewer plane."""
    members = [{"client_id": c.client_id, "mode": c.mode}
               for c in connections.values()]
    total = len(members)
    if limit is not None and total > limit:
        members = members[:limit]
    return members, total


def announce_connect(connections, connection,
                     max_roster: int | None = None) -> None:
    """Send the newcomer the (bounded) roster; announce it to everyone
    else while the roster is within ``max_roster`` — past the bound the
    snapshot's ``total`` is the presence signal (peers see a count grow,
    not one join event per member)."""
    members, total = roster_sample(connections, max_roster)
    if connection.on_signal is not None:
        connection.on_signal(_signal({
            "event": "snapshot", "members": members, "total": total}))
    if max_roster is not None and total > max_roster:
        # Interest-sampled: no per-join member storm past the bound —
        # but the COUNT must still move, or peers' totals drift (the
        # leave path decrements; an unannounced join never increments).
        # Coalesced statelessly: only bucket crossings broadcast, so a
        # join storm costs O(N log N) callbacks total, not O(N^2);
        # between crossings peers' totals are stale by < 1/16 and
        # self-correct at the next crossing (count events are exact).
        if _count_moved(total - 1, total):
            _broadcast_count(connections, connection.client_id, total)
        return
    member = {"client_id": connection.client_id, "mode": connection.mode}
    for other in connections.values():
        if (other.client_id != connection.client_id
                and other.on_signal is not None):
            other.on_signal(_signal({"event": "join", "member": member}))


def _count_moved(before: int, after: int) -> bool:
    """Stateless coalescing rule for count broadcasts (these functions
    hold no per-doc state): announce only when the population crossed a
    ~1/16 bucket boundary. Small rosters (< 32) always announce."""
    def bucket(n: int) -> int:
        return n if n < 32 else n >> (n.bit_length() - 5)
    return bucket(before) != bucket(after)


def _broadcast_count(connections, skip_client_id: str | None,
                     total: int, left: str | None = None) -> None:
    payload = {"event": "count", "total": total}
    if left is not None:
        payload["left"] = left
    for other in connections.values():
        if (other.client_id != skip_client_id
                and other.on_signal is not None):
            other.on_signal(_signal(payload))


def announce_leave(connections, client_id: str,
                   max_roster: int | None = None) -> None:
    """Announce one departure. Past the roster bound the per-member
    leave becomes a coalesced count update carrying the leaver's id (so
    a peer whose SAMPLE held it still drops it at the crossing) —
    totals stay bounded-exact in both directions under sampled
    presence, and a leave storm costs O(N log N) like the join side."""
    total = len(connections)
    if max_roster is not None and total > max_roster:
        if _count_moved(total + 1, total):
            _broadcast_count(connections, None, total, left=client_id)
        return
    for other in connections.values():
        if other.on_signal is not None:
            other.on_signal(_signal({"event": "leave",
                                     "client_id": client_id}))
