"""Audience announcements — the service side of the container's audience
roster (container.ts:1700 region): every connection, including read-only
ones that never reach the quorum, is announced via system signals
(``client_id`` None on the wire; clients reject the shape from peers).

Shared by both service assemblies (RouterliciousService and
LocalCollabServer); connection objects are duck-typed
(client_id / mode / on_signal).
"""

from __future__ import annotations

AUDIENCE_SIGNAL = "__audience__"


def _signal(content: dict) -> dict:
    return {"client_id": None, "content": {"type": AUDIENCE_SIGNAL,
                                           **content}}


def announce_connect(connections, connection) -> None:
    """Send the newcomer the full roster; announce it to everyone else."""
    if connection.on_signal is not None:
        connection.on_signal(_signal({
            "event": "snapshot",
            "members": [{"client_id": c.client_id, "mode": c.mode}
                        for c in connections.values()]}))
    member = {"client_id": connection.client_id, "mode": connection.mode}
    for other in connections.values():
        if (other.client_id != connection.client_id
                and other.on_signal is not None):
            other.on_signal(_signal({"event": "join", "member": member}))


def announce_leave(connections, client_id: str) -> None:
    for other in connections.values():
        if other.on_signal is not None:
            other.on_signal(_signal({"event": "leave",
                                     "client_id": client_id}))
