"""LocalCollabServer — full in-process ordering service for tests/dev.

Reference parity: server/routerlicious/packages/local-server/src/
localDeltaConnectionServer.ts (``LocalDeltaConnectionServer``) + tinylicious:
the alfred front-door, deli sequencer, scriptorium op log, broadcaster
fan-out and snapshot store collapsed into one deterministic in-proc service.

The sequencer is pluggable: the default scalar ``DocumentSequencer`` per
document, or the batched device kernel via
:class:`fluidframework_tpu.server.kernel_host.KernelSequencerHost` — both
produce identical tickets (differentially tested), so the e2e stack runs
unchanged on either.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable

from ..protocol.messages import (
    ClientDetail,
    DocumentMessage,
    MessageType,
    NackMessage,
    ScopeType,
    SequencedDocumentMessage,
)
from ..ops import opcodes as oc
from .sequencer import DocumentSequencer, RawOperation, Ticket


@dataclass
class _Connection:
    client_id: str
    document: "_Document"
    handler: Callable[[list[SequencedDocumentMessage]], None]
    on_nack: Callable[[NackMessage], None] | None = None
    on_signal: Callable[[Any], None] | None = None
    open: bool = True
    mode: str = "write"

    def submit(self, messages: list[DocumentMessage]) -> None:
        assert self.open, "submit on closed connection"
        self.document.server.submit(self.document.doc_id, self.client_id,
                                    messages)

    def signal(self, content: Any) -> None:
        assert self.open, "signal on closed connection"
        self.document.server.signal(self.document.doc_id, self.client_id,
                                    content)

    def close(self) -> None:
        if self.open:
            self.open = False
            self.document.server.disconnect(self.document.doc_id,
                                            self.client_id)


@dataclass
class _Document:
    doc_id: str
    server: "LocalCollabServer"
    sequencer: DocumentSequencer = field(default_factory=DocumentSequencer)
    log: list[SequencedDocumentMessage] = field(default_factory=list)
    connections: dict[str, _Connection] = field(default_factory=dict)
    snapshots: dict[str, dict] = field(default_factory=dict)
    blobs: dict[str, bytes] = field(default_factory=dict)
    # Only ACKED summaries are load-visible (scribe writes the git commit
    # before emitting summaryAck); the attach-time base upload is implicitly
    # acked as the document's root.
    acked_snapshot: str | None = None
    last_broadcast_seq: int = 0
    # Broadcast queue: a client handler may re-entrantly submit (in-proc),
    # sequencing new messages mid-fan-out; they must not overtake the
    # message currently being delivered for connections not yet visited.
    delivery: list[SequencedDocumentMessage] = field(default_factory=list)
    delivering: bool = False


class LocalCollabServer:
    """In-memory multi-document ordering + storage service."""

    def __init__(self, sequencer_factory: Callable[[], DocumentSequencer]
                 = DocumentSequencer, merge_host=None) -> None:
        self._sequencer_factory = sequencer_factory
        self._documents: dict[str, _Document] = {}
        self._client_counter = itertools.count(1)
        self._clock = itertools.count(1)  # deterministic timestamps
        # Optional KernelMergeHost: every sequenced message also feeds the
        # device-resident server replica (server/merge_host.py).
        self.merge_host = merge_host

    def _document(self, doc_id: str) -> _Document:
        if doc_id not in self._documents:
            self._documents[doc_id] = _Document(
                doc_id, self, sequencer=self._sequencer_factory())
        return self._documents[doc_id]

    # -- connection lifecycle (alfred connect_document) -----------------------

    def connect(
        self,
        doc_id: str,
        handler: Callable[[list[SequencedDocumentMessage]], None],
        on_nack: Callable[[NackMessage], None] | None = None,
        on_signal: Callable[[Any], None] | None = None,
        mode: str = "write",
        scopes: tuple[str, ...] = ScopeType.ALL,
    ) -> _Connection:
        document = self._document(doc_id)
        client_id = f"client-{next(self._client_counter)}"
        connection = _Connection(client_id, document, handler, on_nack,
                                 on_signal)
        connection.mode = mode
        document.connections[client_id] = connection
        # Audience wiring (container.ts:1700): announce EVERY connection
        # (read-only ones included — they never reach the quorum).
        from .audience import MAX_ROSTER, announce_connect
        announce_connect(document.connections, connection,
                         max_roster=MAX_ROSTER)
        # Read clients receive the broadcast stream but never enter the
        # quorum or the MSN calculation (the reference sequences joins only
        # for write connections — a reader must not pin minSeq).
        if mode != "read":
            detail = ClientDetail(client_id=client_id, mode=mode,
                                  scopes=scopes)
            self._sequence_raw(document, RawOperation(
                client_id=None,
                type=MessageType.CLIENT_JOIN,
                data=detail,
                timestamp=next(self._clock),
                can_summarize=ScopeType.SUMMARY_WRITE in scopes,
            ))
        return connection

    def disconnect(self, doc_id: str, client_id: str) -> None:
        document = self._document(doc_id)
        connection = document.connections.pop(client_id, None)
        if connection is not None:
            from .audience import MAX_ROSTER, announce_leave
            announce_leave(document.connections, client_id,
                           max_roster=MAX_ROSTER)
        if connection is not None and connection.mode == "read":
            return
        self._sequence_raw(document, RawOperation(
            client_id=None,
            type=MessageType.CLIENT_LEAVE,
            data=client_id,
            timestamp=next(self._clock),
        ))

    # -- op path (alfred submitOp → deli → scriptorium/broadcaster) -----------

    def submit(self, doc_id: str, client_id: str,
               messages: list[DocumentMessage]) -> None:
        document = self._document(doc_id)
        for message in messages:
            raw = RawOperation(
                client_id=client_id,
                type=message.type,
                client_seq=message.client_sequence_number,
                ref_seq=message.reference_sequence_number,
                timestamp=next(self._clock),
                contents=message.contents,
            )
            ticket = document.sequencer.ticket(raw)
            if ticket.kind == oc.OUT_NACK:
                connection = document.connections.get(client_id)
                if connection is not None and connection.on_nack is not None:
                    connection.on_nack(NackMessage(
                        operation=message,
                        sequence_number=ticket.seq,
                        code=403 if ticket.nack_code == oc.NACK_NO_SUMMARY_SCOPE
                        else 400,
                        error_type=ticket.nack_code,
                        message=f"nack:{ticket.nack_code}",
                    ))
                continue
            if ticket.kind == oc.OUT_SEQUENCED:
                self._emit(document, raw, ticket)
                if message.type == MessageType.SUMMARIZE:
                    self._scribe_handle_summary(document, message, ticket)

    def _scribe_handle_summary(self, document: _Document,
                               message: DocumentMessage,
                               ticket: Ticket) -> None:
        """Scribe lambda analog: validate the client summary offer, make it
        durable/load-visible, and sequence the ack into the op stream
        (scribe/lambda.ts:190-250 + summaryWriter.writeClientSummary)."""
        handle = (message.contents or {}).get("handle")
        proposal = {"summary_proposal": {
            "summary_sequence_number": ticket.seq}}

        def nack(reason: str) -> None:
            self._sequence_raw(document, RawOperation(
                client_id=None,
                type=MessageType.SUMMARY_NACK,
                contents={"message": reason, "handle": handle, **proposal},
                timestamp=next(self._clock),
            ))

        offered = document.snapshots.get(handle)
        if offered is None:
            nack(f"unknown summary handle {handle!r}")
            return
        # Ancestry check (scribe validates the proposal against the current
        # summary head): a stale or replayed offer must not roll the acked
        # snapshot back to an older sequence number.
        current = document.snapshots.get(document.acked_snapshot or "")
        offered_seq = (offered or {}).get("sequence_number")
        if not isinstance(offered_seq, int):
            nack("summary content missing sequence_number")
            return
        if current is not None and offered_seq < current["sequence_number"]:
            nack(f"stale summary at seq {offered_seq} < "
                 f"current {current['sequence_number']}")
            return
        document.acked_snapshot = handle
        self._sequence_raw(document, RawOperation(
            client_id=None,
            type=MessageType.SUMMARY_ACK,
            contents={"handle": handle, **proposal},
            timestamp=next(self._clock),
        ))

    def signal(self, doc_id: str, client_id: str, content: Any) -> None:
        """Transient broadcast, never sequenced (alfred submitSignal)."""
        document = self._document(doc_id)
        for connection in list(document.connections.values()):
            if connection.on_signal is not None:
                connection.on_signal({"client_id": client_id,
                                      "content": content})

    def _sequence_raw(self, document: _Document, raw: RawOperation) -> None:
        ticket = document.sequencer.ticket(raw)
        if ticket.kind == oc.OUT_SEQUENCED:
            self._emit(document, raw, ticket)

    def _emit(self, document: _Document, raw: RawOperation,
              ticket: Ticket) -> None:
        # Un-revved carriers (delayed no-ops) are consolidated away: only
        # messages that advanced the sequence number broadcast.
        if ticket.seq <= document.last_broadcast_seq:
            return
        document.last_broadcast_seq = ticket.seq
        sequenced = SequencedDocumentMessage(
            client_id=raw.client_id,
            sequence_number=ticket.seq,
            minimum_sequence_number=ticket.msn,
            client_sequence_number=raw.client_seq,
            reference_sequence_number=raw.ref_seq,
            type=raw.type,
            contents=raw.contents,
            timestamp=raw.timestamp,
            data=raw.data,
        )
        document.log.append(sequenced)
        if self.merge_host is not None:
            self.merge_host.ingest(document.doc_id, sequenced)
        document.delivery.append(sequenced)
        if document.delivering:
            return
        document.delivering = True
        try:
            while document.delivery:
                message = document.delivery.pop(0)
                for connection in list(document.connections.values()):
                    connection.handler([message])
        finally:
            document.delivering = False

    # -- storage (scriptorium/historian equivalents) --------------------------

    def get_deltas(self, doc_id: str, from_seq: int,
                   to_seq: int | None = None) -> list[SequencedDocumentMessage]:
        log = self._document(doc_id).log
        return [m for m in log
                if m.sequence_number > from_seq
                and (to_seq is None or m.sequence_number <= to_seq)]

    def upload_snapshot(self, doc_id: str, snapshot: dict,
                        parent: str | None = None) -> str:
        """Store a summary blob; returns its handle. The first upload of a
        document is its attach-time base and becomes load-visible at once;
        later uploads become visible only via a sequenced summarize→ack.
        With ``parent``, handle stubs (incremental summaries) resolve
        against that stored summary before the blob is stored."""
        document = self._document(doc_id)
        if parent is not None:
            from ..protocol.summary import resolve_handles
            parent_tree = document.snapshots.get(parent)
            if parent_tree is None:
                raise KeyError(f"unknown parent summary {parent!r}")
            snapshot = resolve_handles(snapshot, parent_tree)
        handle = f"{doc_id}/snapshots/{len(document.snapshots)}"
        document.snapshots[handle] = snapshot
        if document.acked_snapshot is None:
            document.acked_snapshot = handle
        return handle

    def get_latest_snapshot(self, doc_id: str) -> dict | None:
        document = self._document(doc_id)
        if document.acked_snapshot is None:
            return None
        return document.snapshots[document.acked_snapshot]

    def create_blob(self, doc_id: str, blob_id: str, data: bytes) -> str:
        """Attachment-blob storage (blobManager.ts:51 upload path)."""
        self._document(doc_id).blobs[blob_id] = bytes(data)
        return blob_id

    def read_blob(self, doc_id: str, blob_id: str) -> bytes:
        return self._document(doc_id).blobs[blob_id]
