"""Tiered hot/cold document residency — serve millions of REGISTERED
documents from a device pool sized for the HOT set.

Reference parity: routerlicious never keeps every document in a lambda's
memory — cold documents exist only as gitrest content-addressed snapshots
plus their Mongo op-log tail (PAPER.md §2.6-§2.7), and the first
``connect_document`` against one loads it into a deli/scriptorium
partition on demand. Here the same tiering runs over the device pool:

* **hot** — the document holds a sequencer row (``KernelSequencerHost``)
  and a map row (``KernelMergeHost``) and serves at full device rate.
* **cold** — the document is ONE content-addressed snapshot in the
  shared :class:`~fluidframework_tpu.server.durable_store.
  GitSnapshotStore` (its sequencer checkpoint + map-row planes + the
  compact per-doc tick index) keyed ``__cold__::<doc_id>``; its op
  history stays in the storm WAL. Zero bytes of host or device RAM.

The first frame (or connect) against a cold document **hydrates** it —
restore the snapshot into a recycled pool row — and documents idle past
the timeout **evict**: settle + durability barrier, upload the per-doc
snapshot, flip its head ref, then blank and recycle the rows
(``KernelSequencerHost.release_doc`` / ``KernelMergeHost.
release_map_row``). Registration is OPEN and store-resident: a doc id
that has never been served costs nothing anywhere but the namespace (the
reference's Mongo ``documents`` collection analog is the snapshot store's
ref files, on disk, not RAM) — which is exactly why steady-state RSS
scales with the hot set, not the registered population.

Safety invariants (chaos-proven, ``residency.mid_hydrate`` /
``residency.mid_evict`` crashpoints):

* **acked ⇒ durable survives eviction.** Eviction barriers on the WAL
  fsync watermark BEFORE uploading the snapshot and flips the head ref
  atomically; the rows are released only after the flip. A kill anywhere
  in between loses ONLY volatile device state — recovery replays the
  global snapshot + WAL and reconverges byte-identically.
* **hydration is restore-only.** Nothing durable moves, so a kill
  mid-hydrate is indistinguishable from never having hydrated.
* **quarantined documents are pinned resident.** Their device rows are
  the readmission evidence; an eviction would snapshot poisoned planes.
* **no eviction while the WAL is degraded.** The snapshot watermark
  cannot barrier on durability with the fsync breaker open.

Hydration storms are admission-gated by a :class:`~fluidframework_tpu.
server.riddler.TokenBucket` with per-DOC claimable reservations: a
refused hydration reserves a future slot once (debited against the
bucket) and ANY client of that doc claims it by returning at/after the
hint — so a cold-doc stampede degrades to hydrations queued at exactly
the bucket's drain rate instead of an OOM or compounding debt.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import numpy as np

from ..ops import map_kernel as mk
from ..utils import CountedLRU, faults
from .merge_host import ChannelKey, _nd_pack, _nd_unpack

#: Format version stamped on every cold-doc snapshot. Readers accept
#: 0..CURRENT and refuse anything newer (a rolled-back binary must not
#: misparse a newer cold tier).
COLD_DOC_VERSION = 1

#: Snapshot-store key prefix for cold-doc heads (the GitSnapshotStore
#: hashes keys into ref paths, so any doc id is safe here).
COLD_KEY_PREFIX = "__cold__::"


class EvictionRefused(RuntimeError):
    """Eviction would violate a safety invariant: the doc is quarantined
    (its device rows are the readmission evidence), the WAL fsync breaker
    is open (the snapshot watermark cannot barrier on durability), or a
    replay is in flight."""


class ResidencyManager:
    """Hot/cold residency over one :class:`~fluidframework_tpu.server.
    storm.StormController` stack. Attaches itself as
    ``storm.residency``; the controller consults it at frame admission
    (hydrate-or-nack), during WAL replay (hydrate-on-first-touch with
    watermark-exact filtering) and after recovery (cold-index trim)."""

    def __init__(self, storm, snapshots=None,
                 max_resident: int | None = None,
                 idle_evict_s: float = 300.0,
                 hydration_rate_per_s: float = 200.0,
                 hydration_burst: float | None = None,
                 cold_handle_cache: int = 4096,
                 host_label: str | None = None,
                 clock=time.monotonic) -> None:
        from .riddler import TokenBucket
        self.storm = storm
        # Cluster identity (parallel/placement.py): cold snapshots are
        # stamped with the host that wrote them, because their compact
        # tick index references THAT host's WAL — a doc hydrating on
        # another host (live migration over the shared store) must not
        # resolve foreign tick ids into its own WAL. None (single-host)
        # keeps the round-12 behavior bit-for-bit.
        self.host_label = host_label
        #: doc -> {origin host -> its tick index}: the migrated doc's
        #: pre-migration catch-up indexes, carried through subsequent
        #: evictions so every host keeps serving its own WAL segments.
        self.foreign_ticks: dict[str, dict[str, list]] = {}
        self.snapshots = (snapshots if snapshots is not None
                          else storm.snapshots)
        if self.snapshots is None:
            raise ValueError(
                "ResidencyManager needs a snapshot store — cold documents "
                "live there (pass snapshots= here or on the controller)")
        self.max_resident = max_resident
        self.idle_evict_s = idle_evict_s
        self._clock = clock
        # Hydration admission: one bucket for the host's hydration I/O
        # budget (snapshot read + row restore per hydration). reserve()
        # refusals ladder a stampede out at the drain rate; the per-doc
        # reservation below makes the refusal CLAIMABLE so retries never
        # re-debit (the AdmissionController.admit_connect pattern).
        self.hydrations = TokenBucket(hydration_rate_per_s,
                                      hydration_burst, clock=clock)
        self._reservations: dict[str, float] = {}  # doc -> claimable at
        #: doc -> last-touch clock. Python dicts are insertion-ordered and
        #: touch() re-inserts, so iteration order IS the LRU order.
        self.resident: dict[str, float] = {}
        metrics = storm.merge_host.metrics
        self._metrics = metrics
        # Cold-doc handle cache over the store's head refs: RAM stays
        # O(cache), the store stays the authority (restart-safe; a miss
        # is one ref-file read).
        self._cold_handles = CountedLRU(max(1, cold_handle_cache),
                                        registry=metrics,
                                        prefix="residency.handle_cache")
        self._known_cold = 0  # evictions minus cold re-hydrations, this life
        self.stats = {"hydrations": 0, "cold_hydrations": 0,
                      "evictions": 0, "hydration_nacks": 0,
                      "evict_refusals": 0, "replay_hydrations": 0}
        # Cold snapshots read during a recovery replay, cached so a doc
        # touched by many replayed ticks reads its snapshot once.
        self._replay_cache: dict[str, dict | None] = {}
        # evict() flushes, flush pumps the service, and the service's
        # idle pass drives evict_idle — the guard keeps that cycle from
        # re-entering the sweep mid-eviction.
        self._sweeping = False
        # Adopt rows already live on the hosts (docs served before the
        # manager attached).
        now = self._clock()
        for doc in storm.seq_host._rows:
            self.resident[doc] = now
        storm.residency = self
        self._update_gauges()

    # -- directory -------------------------------------------------------------

    @staticmethod
    def _cold_key(doc_id: str) -> str:
        return COLD_KEY_PREFIX + doc_id

    def is_resident(self, doc_id: str) -> bool:
        return doc_id in self.resident

    def cold_handle(self, doc_id: str) -> str | None:
        """Snapshot handle of the doc's cold head, or None when the doc
        has never been evicted (fresh registration / purely hot)."""
        cached = self._cold_handles.get(doc_id)
        if cached is not None:
            return cached or None  # "" caches a confirmed absence
        handle = self.snapshots.head(self._cold_key(doc_id))
        self._cold_handles.put(doc_id, handle or "")
        return handle

    def cold_doc_ticks(self, doc_id: str) -> list[tuple[int, int, int]]:
        """A COLD doc's compact catch-up index, read from its cold head
        WITHOUT hydrating — a gap fetch is a read and must not churn the
        pool. Empty for fresh registrations (no cold head). Tick ids
        resolve into THIS host's WAL only: a foreign-home snapshot (the
        doc migrated away and was re-evicted elsewhere) serves this
        host's segment from its ``foreign_ticks`` carry-through."""
        handle = self.cold_handle(doc_id)
        if not handle:
            return []
        snap = self.snapshots.get(self._cold_key(doc_id), handle)
        if snap is None:
            # The cached head was superseded by ANOTHER host's eviction
            # and its chunks GC'd (cluster re-home + re-evict): refresh
            # from the authoritative ref and retry once.
            handle = self.snapshots.head(self._cold_key(doc_id))
            self._cold_handles.put(doc_id, handle or "")
            snap = (self.snapshots.get(self._cold_key(doc_id), handle)
                    if handle else None)
            if snap is None:
                return []
        home = snap.get("home")
        if home is not None and home != self.host_label:
            return [tuple(t) for t in snap.get(
                "foreign_ticks", {}).get(self.host_label or "", ())]
        return [tuple(t) for t in snap.get("doc_ticks", ())]

    def adopt_cold(self, doc_id: str, handle: str) -> None:
        """Register an externally-written cold head (the history
        plane's branch-fork seed writes the cold record itself): cache
        the handle and count the doc cold — the first connect/frame
        hydrates it through the normal admission-gated path."""
        assert doc_id not in self.resident, doc_id
        self._cold_handles.put(doc_id, handle)
        self._known_cold += 1
        self._update_gauges()

    def touch(self, doc_id: str, now: float | None = None) -> None:
        """Refresh a resident doc's idle clock (re-insert = LRU order)."""
        self.resident.pop(doc_id, None)
        self.resident[doc_id] = self._clock() if now is None else now

    # -- frame admission (the storm._admit seam) -------------------------------

    def admit_docs(self, docs: list[str]
                   ) -> tuple[float | None, str | None]:
        """Residency gate for one validated frame's doc set: touch the
        resident docs and synchronously hydrate the cold ones. Returns
        ``(None, None)`` once every doc is resident, else
        ``(retry_after_s, code)`` for the busy-nack — ``"hydrating"``
        when the token bucket laddered the stampede out, ``"busy"`` when
        the pool has no evictable slot."""
        now = self._clock()
        cold = [d for d in docs if d not in self.resident]
        if not cold:
            for d in docs:
                self.touch(d, now)
            return None, None
        # Token gate first (cheap), one token per cold doc; capacity
        # (which may pay an eviction) only for admitted frames.
        spent = 0
        worst: float | None = None
        for doc in cold:
            retry = self._gate_hydration(doc, now)
            if retry is None:
                spent += 1
            elif worst is None or retry > worst:
                worst = retry
        if worst is not None:
            # Whole-frame refusal: refund the tokens freshly spent in
            # this call (claimed/ladder reservations stand — they are the
            # stampede spreading mechanism).
            if spent:
                self.hydrations.refund("hydrate", spent)
            self.stats["hydration_nacks"] += 1
            return worst, "hydrating"
        retry = self._free_slots(len(cold), exclude=set(docs))
        if retry is not None:
            if spent:
                self.hydrations.refund("hydrate", spent)
            return retry, "busy"
        for doc in cold:
            self.hydrate(doc)
        for d in docs:
            self.touch(d, now)
        return None, None

    def ensure_resident(self, doc_id: str, gate: bool = True
                        ) -> float | None:
        """Connect-path residency: hydrate a cold doc (admission-gated
        unless ``gate=False`` — in-process callers), returning the
        ``retry_after_s`` hint on refusal and None once resident."""
        if doc_id in self.resident:
            self.touch(doc_id)
            return None
        now = self._clock()
        if gate:
            retry = self._gate_hydration(doc_id, now)
            if retry is not None:
                self.stats["hydration_nacks"] += 1
                return retry
        retry = self._free_slots(1, exclude={doc_id})
        if retry is not None:
            if gate:
                self.hydrations.refund("hydrate")
            return retry
        self.hydrate(doc_id)
        return None

    def _gate_hydration(self, doc: str, now: float) -> float | None:
        """One doc through the hydration bucket with a CLAIMABLE per-doc
        reservation: the refusal debits the bucket once; any client of
        the doc claims that slot by returning at/after the hint."""
        at = self._reservations.get(doc)
        if at is not None:
            wait = at - now
            if wait > 1e-9:
                return wait  # came back early; the same slot stands
            del self._reservations[doc]
            self._metrics.gauge("residency.hydrating_docs").set(
                len(self._reservations))
            return None  # claiming the already-debited slot
        if len(self._reservations) > 4096:
            # Docs whose clients never came back leave unclaimed entries;
            # sweep the long-expired ones (the bounded-memory rule).
            from .riddler import TokenBucket
            horizon = now - TokenBucket.RESERVE_HORIZON_S
            for key in [d for d, t in self._reservations.items()
                        if t < horizon]:
                del self._reservations[key]
        retry, reserved = self.hydrations.reserve("hydrate")
        if retry is not None and reserved:
            self._reservations[doc] = now + retry
            self._metrics.gauge("residency.hydrating_docs").set(
                len(self._reservations))
        return retry

    def _free_slots(self, need: int, exclude: set[str]) -> float | None:
        """Make room for ``need`` hydrations, evicting LRU residents if
        the pool is capped. Returns a retry hint when no evictable slot
        exists (every resident is quarantined/excluded/refusing)."""
        if self.max_resident is None:
            return None
        while len(self.resident) + need > self.max_resident:
            victim = None
            for doc in self.resident:  # LRU order
                if doc in exclude or doc in self.storm.quarantined:
                    continue
                victim = doc
                break
            if victim is None:
                return self.storm.busy_retry_s
            try:
                self.evict(victim, reason="capacity")
            except EvictionRefused:
                return self.storm.busy_retry_s
        return None

    # -- hydration -------------------------------------------------------------

    def hydrate(self, doc_id: str) -> bool:
        """Load a cold doc into the device pool (restore-only: nothing
        durable moves, so a kill mid-hydrate loses nothing). Returns True
        when a cold snapshot was restored, False for a fresh registration
        (rows lazy-allocate on the doc's first tick)."""
        assert doc_id not in self.resident, doc_id
        t0 = time.perf_counter()
        # Authoritative head read, NOT the cached handle: in a cluster
        # another host may have flipped this doc's cold head since we
        # cached ours (live migration re-homes + re-evictions), and the
        # superseded snapshot may already be GC'd — hydrating from a
        # stale handle would silently restore nothing. One ref-file
        # read on the already-expensive hydration path.
        handle = self.snapshots.head(self._cold_key(doc_id))
        self._cold_handles.put(doc_id, handle or "")
        snap = (self.snapshots.get(self._cold_key(doc_id), handle)
                if handle else None)
        restored = False
        if snap is not None:
            self._restore(doc_id, snap)
            restored = True
            self.stats["cold_hydrations"] += 1
            self._known_cold = max(0, self._known_cold - 1)
        else:
            faults.crashpoint("residency.mid_hydrate")
        self.resident[doc_id] = self._clock()
        self.stats["hydrations"] += 1
        self._metrics.counter("residency.hydrations").inc()
        self._metrics.histogram("residency.hydrate_s").observe(
            time.perf_counter() - t0)
        self._update_gauges()
        return restored

    def _restore(self, doc_id: str, snap: dict) -> None:
        """Install one cold snapshot into recycled pool rows."""
        version = snap.get("format_version", 0)
        if not 0 <= version <= COLD_DOC_VERSION:
            raise ValueError(
                f"cold-doc snapshot format v{version} is newer than this "
                f"reader (max v{COLD_DOC_VERSION})")
        storm = self.storm
        from .sequencer import SequencerCheckpoint
        storm.seq_host.restore(doc_id,
                               SequencerCheckpoint(**snap["sequencer"]))
        # Chaos kill class "mid-hydrate": the sequencer row is restored,
        # the map row is NOT — the half-hydrated doc is volatile only and
        # recovery re-hydrates from the same durable snapshot.
        faults.crashpoint("residency.mid_hydrate")
        m = snap.get("map_row")
        if m is not None:
            mrow = storm._storm_mrow(doc_id)
            xs = storm.merge_host._xstate
            s_live = xs.present.shape[1]
            vals = {"present": np.zeros(s_live, np.bool_),
                    "value": np.zeros(s_live, np.int32),
                    "vseq": np.full(s_live, -1, np.int32)}
            for f in ("present", "value", "vseq"):
                plane = _nd_unpack(m[f])
                assert plane.shape[0] <= s_live, (
                    f"cold map row wider than live "
                    f"({plane.shape[0]} > {s_live})")
                vals[f][:plane.shape[0]] = plane
            row = mrow.row
            storm.merge_host._xstate = mk.MapState(
                present=xs.present.at[row].set(vals["present"]),
                value=xs.value.at[row].set(vals["value"]),
                vseq=xs.vseq.at[row].set(vals["vseq"]),
                cleared_seq=xs.cleared_seq.at[row].set(
                    np.int32(m["cleared_seq"])))
            mrow.last_seq = m["last_seq"]
        # The compact catch-up index travels with the doc. During
        # recovery the __init__ blob scan already rebuilt a COMPLETE
        # index (it covers post-snapshot ticks too) — never overwrite it
        # with the snapshot's shorter one. A FOREIGN-home snapshot (live
        # migration over the shared store) must not adopt at all: its
        # tick ids reference the origin host's WAL, and adopting them
        # here would resolve catch-up reads into the wrong blobs — the
        # origin index is carried as foreign_ticks instead, so every
        # host keeps serving its own WAL segments.
        home = snap.get("home")
        if home is not None and home != self.host_label:
            carried = dict(snap.get("foreign_ticks", {}))
            if snap.get("doc_ticks"):
                carried[home] = [list(t) for t in snap["doc_ticks"]]
            # A doc migrating BACK to a prior home re-adopts that
            # home's own segment into the live index (its tick ids
            # resolve HERE; the next local eviction then exports a
            # complete local doc_ticks again) — leaving it only in
            # foreign_ticks would drop this host's pre-migration
            # segment from every later catch-up read.
            own = (carried.pop(self.host_label, None)
                   if self.host_label is not None else None)
            if own and doc_id not in storm._doc_ticks:
                storm._doc_ticks[doc_id] = [tuple(t) for t in own]
            if carried:
                self.foreign_ticks[doc_id] = carried
        else:
            if snap.get("doc_ticks") and doc_id not in storm._doc_ticks:
                storm._doc_ticks[doc_id] = [tuple(t)
                                            for t in snap["doc_ticks"]]
            if snap.get("foreign_ticks"):
                self.foreign_ticks[doc_id] = dict(snap["foreign_ticks"])
        if doc_id not in storm.doc_tick_counts:
            storm.doc_tick_counts[doc_id] = snap.get("tick_count", 0)

    # -- eviction --------------------------------------------------------------

    def evict(self, doc_id: str, reason: str = "idle") -> str:
        """Demote one resident doc to the cold tier: settle + durability
        barrier, upload its snapshot, flip the head ref atomically, THEN
        release the device rows and trim the per-doc bookkeeping. Raises
        :class:`EvictionRefused` when the invariants forbid it. Returns
        the cold snapshot handle."""
        storm = self.storm
        if doc_id not in self.resident:
            raise KeyError(f"{doc_id!r} is not resident")
        if doc_id in storm.quarantined:
            self.stats["evict_refusals"] += 1
            raise EvictionRefused(
                f"{doc_id!r} is quarantined — its device rows are the "
                "readmission evidence; readmit before evicting")
        megadoc = getattr(storm, "megadoc", None)
        if megadoc is not None and (megadoc.is_promoted(doc_id)
                                    or megadoc.parent_of(doc_id)):
            # A promoted doc's live state spans lane rows + the combiner
            # mirror; the per-doc cold record would capture only the
            # frozen baseline row. Mega docs are pinned resident.
            self.stats["evict_refusals"] += 1
            raise EvictionRefused(
                f"{doc_id!r} is mega-promoted (write scale-out); demote "
                "before evicting")
        if storm._replay:
            self.stats["evict_refusals"] += 1
            raise EvictionRefused("eviction during WAL replay")
        if storm._in_round:
            # The pump inside _flush_round reached an idle pass: the
            # cohort being assembled may include this doc — refuse; the
            # next top-level sweep evicts it.
            self.stats["evict_refusals"] += 1
            raise EvictionRefused("eviction during a serving round")
        if storm.wal_degraded:
            self.stats["evict_refusals"] += 1
            raise EvictionRefused(
                "WAL fsync breaker open: the cold snapshot's watermark "
                "cannot barrier on durability")
        if getattr(storm, "replication", None) is not None \
                and storm.replication.fenced:
            # A demoted ex-leader flipping a cold head would clobber the
            # promoted incarnation's record — fenced hosts never write
            # shared-store heads.
            self.stats["evict_refusals"] += 1
            raise EvictionRefused(
                "eviction on a fenced (demoted) leader: cold-head flips "
                "belong to the promoted incarnation")
        t0 = time.perf_counter()
        # Settle everything: bus-path ops (client joins/leaves, per-op
        # submits) sequence first — a doc whose JOIN is still buffered
        # has no device row yet — then the storm frames serve or shed,
        # and the durability watermark pins past every harvested tick
        # (the snapshot must never claim state the WAL could still
        # lose). The sweep guard blocks the pump's idle pass from
        # re-entering eviction under us.
        prev_sweeping, self._sweeping = self._sweeping, True
        try:
            storm.service.pump()
            storm.flush()
        finally:
            self._sweeping = prev_sweeping
        if doc_id not in storm.seq_host._rows:
            # Registered/connected but never served one op: nothing on
            # device to demote, nothing new to make durable. Drop the
            # residency entry; any existing cold head stays authoritative.
            self.resident.pop(doc_id)
            self.stats["evictions"] += 1
            self._update_gauges()
            return self.cold_handle(doc_id) or ""
        if storm._group_wal is not None:
            from .durable_store import WalDegradedError
            try:
                storm._group_wal.sync()
            except WalDegradedError as err:
                self.stats["evict_refusals"] += 1
                raise EvictionRefused(
                    "WAL degraded during the eviction barrier") from err
        if doc_id in storm.quarantined:
            # The settle flush itself tripped the sentinel: the poisoned
            # row must never become the cold rebuild source.
            self.stats["evict_refusals"] += 1
            raise EvictionRefused(
                f"{doc_id!r} quarantined during the eviction flush")
        snap = self._export(doc_id)
        key = self._cold_key(doc_id)
        superseded = self.cold_handle(doc_id)
        handle = self.snapshots.upload(key, snap)
        # Chaos kill class "mid-evict": snapshot uploaded, head ref NOT
        # yet flipped, rows still live — recovery sees the doc resident
        # (global snapshot + WAL) and the orphan upload is harmless.
        faults.crashpoint("residency.mid_evict")
        self.snapshots.set_head(key, handle)
        if superseded and superseded != handle:
            # Cold-store GC: the old head's unreferenced blobs delete on
            # the flip (content-addressed refcounts — chunks another
            # doc's snapshot shares survive). A churned cold doc's disk
            # cost stays ONE snapshot, not one per eviction. Kill-window
            # safety: the release runs after the flip, so a crash in
            # between leaks at most one superseded snapshot.
            release = getattr(self.snapshots, "release", None)
            if release is not None:
                try:
                    release(key, superseded)
                except Exception:
                    pass  # GC is best-effort; serving state is already safe
        # Kill window between the flip and the release: the doc is
        # durable BOTH ways (cold head == live state), so either recovery
        # choice reconverges byte-identically.
        faults.crashpoint("residency.post_evict")
        storm.seq_host.release_doc(doc_id)
        ckey = ChannelKey(doc_id, storm.datastore, storm.channel)
        if ckey in storm.merge_host._map_rows:
            storm.merge_host.release_map_row(ckey)
        # Per-doc bookkeeping rides the snapshot, not RAM (the O(hot)
        # bound): the tick index and telemetry count restore on hydrate.
        storm._doc_ticks.pop(doc_id, None)
        storm.doc_tick_counts.pop(doc_id, None)
        self.foreign_ticks.pop(doc_id, None)  # exported above
        self.resident.pop(doc_id)
        self._cold_handles.put(doc_id, handle)
        self._known_cold += 1
        self.stats["evictions"] += 1
        self._metrics.counter("residency.evictions").inc()
        self._metrics.histogram("residency.evict_s").observe(
            time.perf_counter() - t0)
        self._update_gauges()
        return handle

    def evict_idle(self, now: float | None = None,
                   max_evictions: int | None = None) -> list[str]:
        """Evict every resident doc idle past ``idle_evict_s`` (the
        deli-checkIdleClients analog at DOC granularity — the service's
        idle-ejection pass drives this). Quarantined docs are skipped
        (pinned resident); refusals leave the doc resident."""
        if self._sweeping:
            return []  # re-entered through evict → flush → pump
        now = self._clock() if now is None else now
        evicted: list[str] = []
        self._sweeping = True
        try:
            for doc, last in list(self.resident.items()):
                if now - last < self.idle_evict_s:
                    break  # LRU order: everything after is fresher
                if doc in self.storm.quarantined:
                    continue
                try:
                    self.evict(doc, reason="idle")
                except EvictionRefused:
                    continue
                evicted.append(doc)
                if max_evictions is not None \
                        and len(evicted) >= max_evictions:
                    break
        finally:
            self._sweeping = False
        return evicted

    def _export(self, doc_id: str) -> dict:
        storm = self.storm
        snap: dict[str, Any] = {
            "kind": "cold-doc",
            "format_version": COLD_DOC_VERSION,
            "doc": doc_id,
            # Every tick BELOW the watermark is reflected in this
            # snapshot; hydration during recovery drops the doc's
            # replayed entries below it (watermark-exact, no double
            # apply, no reliance on dedup).
            "tick_watermark": storm._tick_counter,
            "sequencer": dataclasses.asdict(
                storm.seq_host.checkpoint(doc_id)),
            "map_row": None,
            "doc_ticks": [list(t)
                          for t in storm._doc_ticks.get(doc_id, ())],
            "tick_count": storm.doc_tick_counts.get(doc_id, 0),
        }
        if self.host_label is not None:
            snap["home"] = self.host_label
            if doc_id in self.foreign_ticks:
                snap["foreign_ticks"] = self.foreign_ticks[doc_id]
        ckey = ChannelKey(doc_id, storm.datastore, storm.channel)
        mrow = storm.merge_host._map_rows.get(ckey)
        if mrow is not None:
            xs = storm.merge_host._xstate
            row = mrow.row
            snap["map_row"] = {
                "present": _nd_pack(np.asarray(xs.present[row])),
                "value": _nd_pack(np.asarray(xs.value[row])),
                "vseq": _nd_pack(np.asarray(xs.vseq[row])),
                "cleared_seq": int(np.asarray(xs.cleared_seq[row])),
                "last_seq": mrow.last_seq,
            }
        return snap

    # -- recovery (storm.recover / _replay_wal seams) --------------------------

    def adopt_resident(self) -> None:
        """Mark every doc the global snapshot restored as resident
        (called by recover() between the restore and the WAL replay)."""
        now = self._clock()
        for doc in self.storm.seq_host._rows:
            self.resident.setdefault(doc, now)
        self._update_gauges()

    def prepare_replay(self, entries: list, tick: int) -> list:
        """Residency-aware WAL replay filter for one tick's doc entries:
        resident docs replay as-is; a cold doc hydrates ON FIRST TOUCH
        from its cold head — and its entries for ticks BELOW the cold
        snapshot's watermark are dropped (the snapshot already reflects
        them, watermark-exact). Fresh docs (no cold head) replay into
        lazily-allocated rows exactly like live traffic. The pool cap is
        ignored during replay (recovery must not write new cold
        snapshots mid-replay); idle eviction re-tiers afterwards."""
        out = []
        now = self._clock()
        for entry in entries:
            doc = entry[0]
            if doc in self.resident:
                out.append(entry)
                continue
            if doc in self._replay_cache:
                snap = self._replay_cache[doc]
            else:
                handle = self.cold_handle(doc)
                snap = (self.snapshots.get(self._cold_key(doc), handle)
                        if handle else None)
                self._replay_cache[doc] = snap
            if snap is None:
                self.resident[doc] = now  # fresh doc: adopt, rows lazy
                out.append(entry)
                continue
            home = snap.get("home")
            if (home is None or home == self.host_label) \
                    and tick < snap.get("tick_watermark", 0):
                continue  # already inside the cold snapshot
            # A FOREIGN-home snapshot's watermark counts the ORIGIN
            # host's ticks — it never filters local entries (every
            # local entry for a migrated-in doc post-dates the
            # hydration by construction).
            self._restore(doc, snap)
            self.resident[doc] = now
            self.stats["replay_hydrations"] += 1
            out.append(entry)
        return out

    def after_recover(self) -> None:
        """Post-recovery trim: docs whose ticks the __init__ blob scan
        indexed but which are COLD (head present, not restored, not
        touched by the replayed tail) drop their in-RAM index — it lives
        in their cold snapshot and restores on hydrate. Keeps a restarted
        host's RAM O(hot), not O(ever-served)."""
        storm = self.storm
        self._replay_cache.clear()
        self.adopt_resident()
        for doc in list(storm._doc_ticks):
            if doc in self.resident:
                continue
            if self.cold_handle(doc):
                storm._doc_ticks.pop(doc, None)
                storm.doc_tick_counts.pop(doc, None)
        self._update_gauges()

    # -- observability ---------------------------------------------------------

    def _update_gauges(self) -> None:
        self._metrics.gauge("residency.hot_docs").set(len(self.resident))
        self._metrics.gauge("residency.known_cold_docs").set(
            self._known_cold)
        # "Hydrating" = cold docs holding a claimable reservation (their
        # clients were laddered out and will return at the hint).
        self._metrics.gauge("residency.hydrating_docs").set(
            len(self._reservations))
        rss = _rss_mb()
        if rss is not None:
            self._metrics.gauge("residency.rss_mb").set(rss)


def _rss_mb() -> float | None:
    """Current (not peak) resident set size in MiB; None off-Linux."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        import os
        return pages * os.sysconf("SC_PAGE_SIZE") / (1024 * 1024)
    except (OSError, ValueError, IndexError):
        return None


__all__ = ["ResidencyManager", "EvictionRefused", "COLD_DOC_VERSION"]
