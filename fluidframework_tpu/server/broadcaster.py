"""Broadcast viewer plane — serve one hot document to 100k read-only
viewers without touching the merge path.

Reference parity: the broadcaster lambda + Redis socket.io-adapter tier
(PAPER §2.6/§2.9) — huge live-event audiences consume the sequenced op
stream and summaries through a dedicated fan-out tier; they never enter
admission accounting, sequencing, or per-connection ack bookkeeping.
Here that tier is :class:`ViewerPlane`:

* a **viewer** is a ``mode="viewer"`` connect (alfred / routerlicious):
  no CLIENT_JOIN, no quorum entry, no deli row, no admission token
  debit — it joins the document's room in the native fan-out service
  (``native/fanout.cpp``) and drains broadcast frames.
* each serving tick's broadcast frame is serialized **once per doc per
  tick** (``codec.encode_viewer_tick_body`` for storm ticks;
  ``codec.encode_ops_event``'s shared :class:`BroadcastBatch` body on
  the per-op path) and fanned out in **O(batch) native writes** — one
  ``fanout_publish_batch`` call however many documents ticked, one
  refcounted payload however many viewers the room holds.
* **slow viewers lag-drop, never stall the tick**: every viewer
  subscriber carries a shallow per-sub queue bound
  (``fanout_set_queue_limit``); a viewer whose queue overflows (or whose
  transport probe reports a deep outbox) is dropped from the room and
  handed a ``viewer_resync`` directive — it catches up from the latest
  snapshot + ``get_deltas`` (which serves cold docs from their cold-head
  tick index without hydrating, the round-12 read path) and re-enters
  the live stream via ``viewer_resume``.
* **join storms are admission-gated** through the existing
  :class:`~fluidframework_tpu.server.riddler.TokenBucket` reservation
  machinery: a refused join debits the bucket once and the (doc,
  client) reservation is CLAIMABLE at the hint — a 100k-viewer stampede
  drains at exactly the bucket rate instead of re-colliding.
* presence is **interest-sampled** (server/audience.py shape): a new
  viewer receives a bounded roster sample plus the exact total, and
  peers receive coalesced count updates — never one join event per
  member.
"""

from __future__ import annotations

import time
from typing import Any, Callable

from ..protocol.codec import (
    BroadcastBatch,
    RawBody,
    encode_body,
    encode_ops_event,
    encode_viewer_tick_body,
)
from ..utils import MetricsRegistry


class _Viewer:
    """One registered viewer session: its fan-out subscriber, transport
    push, and lag bookkeeping. ``sub`` is None while lag-dropped
    (awaiting ``viewer_resume``)."""

    __slots__ = ("vid", "doc_id", "push", "pending_probe", "sub",
                 "lag_drops", "delivered")

    def __init__(self, vid: str, doc_id: str,
                 push: Callable[[Any], None],
                 pending_probe: Callable[[], int] | None) -> None:
        self.vid = vid
        self.doc_id = doc_id
        self.push = push
        self.pending_probe = pending_probe
        self.sub: int | None = None
        self.lag_drops = 0
        self.delivered = 0


class ViewerConnection:
    """Duck-typed connection for in-process ``mode="viewer"`` connects
    (routerlicious.connect): read-only — ``submit`` raises; payloads
    arrive through the handler exactly as the wire would carry them
    (dicts for control events, :class:`RawBody` broadcast frames)."""

    def __init__(self, plane: "ViewerPlane", vid: str,
                 doc_id: str) -> None:
        self._plane = plane
        self.client_id = vid
        self.doc_id = doc_id
        self.mode = "viewer"
        self.open = True
        self.on_closed: Callable[[], None] | None = None

    def submit(self, messages) -> None:
        raise PermissionError("viewer connections are read-only")

    def signal(self, content) -> None:
        raise PermissionError("viewer connections are read-only")

    def close(self) -> None:
        if self.open:
            self.open = False
            self._plane.leave(self.client_id)


class ViewerPlane:
    """The read-only fan-out tier over one service assembly. Attaches
    itself as ``service.viewers``; the storm harvest and the per-op
    broadcaster publish through it, the front doors join/leave viewer
    sessions through it."""

    #: Room-name prefix in the shared fan-out service: viewer rooms are
    #: namespaced apart from the writer-connection rooms the service's
    #: own ``_drain_fanout`` consumes.
    ROOM_PREFIX = "v::"

    def __init__(self, service, fanout=None,
                 metrics: MetricsRegistry | None = None,
                 join_rate_per_s: float = 2000.0,
                 join_burst: float | None = None,
                 tenant_join_rate_per_s: float | None = None,
                 tenant_join_burst: float | None = None,
                 max_lag_frames: int = 256,
                 transport_lag_frames: int = 1024,
                 roster_sample: int = 16,
                 clock: Callable[[], float] = time.monotonic) -> None:
        from .riddler import TokenBucket
        self.service = service
        self._own_fanout = fanout
        self.metrics = metrics if metrics is not None \
            else getattr(service, "metrics", None) or MetricsRegistry()
        self._clock = clock
        #: Per-viewer fan-out queue bound: a viewer this many frames
        #: behind the broadcast head lag-drops to a resync instead of
        #: buffering on (the per-room outbox bound of fanout.cpp).
        self.max_lag_frames = max_lag_frames
        #: Transport-probe bound (session outbox depth) — the second lag
        #: signal, for transports whose backpressure the fan-out queue
        #: cannot see.
        self.transport_lag_frames = transport_lag_frames
        self.roster_sample = roster_sample
        # Join-storm admission: one bucket for the plane, per-(doc,
        # client) CLAIMABLE reservations (the admit_connect /
        # residency-hydration pattern — a refusal debits once; the
        # client claims the slot by returning at/after the hint).
        self.joins = TokenBucket(join_rate_per_s, join_burst, clock=clock)
        # Per-tenant viewer-join budget (the round-17 noisy-neighbor
        # extension of the read side): one tenant's 100k-viewer event
        # must not consume the whole PLANE's join budget and lock every
        # other tenant's viewers out. Stacked UNDER the plane bucket —
        # the tenant tier debits first, and a plane-tier refusal refunds
        # it (the AdmissionController two-tier pattern). None (default)
        # = plane-wide gating only, the pre-QoS behavior.
        self.tenant_joins = (
            TokenBucket(tenant_join_rate_per_s, tenant_join_burst,
                        clock=clock)
            if tenant_join_rate_per_s is not None else None)
        self._reservations: dict[tuple[str, str], float] = {}
        self._next_vid = 1
        self._viewers: dict[str, _Viewer] = {}
        self._rooms: dict[str, dict[str, _Viewer]] = {}
        # Per-room (members, subs-array) cache for the batched drain —
        # invalidated on any membership change.
        self._room_arrays: dict[str, tuple] = {}
        #: doc -> last sequenced seq published on this plane (the viewer
        #: hello's stream position; catch-up reads cover anything older).
        self._last_seq: dict[str, int] = {}
        #: doc -> viewer count at the last presence announce (coalescing
        #: state: announce when the population moved enough to matter).
        self._announced: dict[str, int] = {}
        self.stats = {"joins": 0, "leaves": 0, "join_nacks": 0,
                      "tick_encodes": 0, "ops_encodes": 0,
                      "lag_drops": 0, "resumes": 0,
                      "presence_updates": 0, "broadcast_bytes": 0,
                      "delivered_frames": 0}
        service.viewers = self

    # -- fan-out backend -------------------------------------------------------

    @property
    def fanout(self):
        """The delivery spine, created lazily so an assembly that never
        sees a viewer pays nothing: the service's own fan-out when one is
        configured, else a plane-private instance (native when the
        toolchain allows — the O(batch) publish + refcounted payloads)."""
        if self._own_fanout is None:
            service_fanout = getattr(self.service, "fanout", None)
            if service_fanout is not None:
                self._own_fanout = service_fanout
            else:
                from ..native.fanout import make_fanout
                self._own_fanout = make_fanout()
        return self._own_fanout

    def _room(self, doc_id: str) -> str:
        return self.ROOM_PREFIX + doc_id

    def has_viewers(self, doc_id: str) -> bool:
        return bool(self._rooms.get(doc_id))

    def room_size(self, doc_id: str) -> int:
        return len(self._rooms.get(doc_id, ()))

    @property
    def active_rooms(self) -> int:
        return len(self._rooms)

    # -- join / leave ----------------------------------------------------------

    def _tenant_nack(self, tenant_id: str | None, retry: float) -> float:
        self.stats["join_nacks"] += 1
        if tenant_id is not None:
            self.metrics.counter(
                f"viewer.tenant.{tenant_id}.join_nacks").inc()
        return retry

    def admit_join(self, doc_id: str,
                   client_key: str | None = None,
                   tenant_id: str | None = None) -> float | None:
        """Viewer-join admission (the storm gate for 100k viewers
        arriving at a live event's start): None admits; a refusal
        returns ``retry_after_s`` and — when ``client_key`` is given —
        reserves a claimable slot so the retry never re-debits.
        ``tenant_id`` (the SESSION's validated tenant) additionally
        debits that tenant's join budget when one is configured — a
        plane-tier refusal refunds it."""
        # Claims are namespaced by tenant: client_key is CLIENT-
        # controlled, so a reservation must only be claimable by the
        # tenant that paid for it — a cross-tenant claim on a guessed
        # key would admit past an exhausted tenant budget for free and
        # steal the payer's slot.
        rkey = None
        if client_key is not None:
            rkey = (doc_id, client_key if tenant_id is None
                    else f"{tenant_id}:{client_key}")
        if self.tenant_joins is not None and tenant_id is not None:
            if rkey is None or rkey not in self._reservations:
                # A claim of an existing reservation already paid the
                # tenant tier when it was reserved — never re-debit it.
                retry = self.tenant_joins.try_consume(
                    f"tenant/{tenant_id}")
                if retry is not None:
                    return self._tenant_nack(tenant_id, retry)
        if client_key is None:
            retry = self.joins.try_consume(f"viewers/{doc_id}")
            if retry is not None:
                self.stats["join_nacks"] += 1
                if self.tenant_joins is not None and tenant_id is not None:
                    self.tenant_joins.refund(f"tenant/{tenant_id}")
            return retry
        reserved_at = self._reservations.get(rkey)
        now = self._clock()
        if reserved_at is not None:
            wait = reserved_at - now
            if wait <= 1e-9:
                del self._reservations[rkey]
                return None  # claiming the already-debited slot
            self.stats["join_nacks"] += 1
            return wait  # came back early; the same slot stands
        if len(self._reservations) > 8192:
            # Viewers that never returned leave unclaimed entries; sweep
            # the long-expired ones (the bounded-memory rule every
            # reservation table here follows).
            from .riddler import TokenBucket
            horizon = now - TokenBucket.RESERVE_HORIZON_S
            for key in [k for k, at in self._reservations.items()
                        if at < horizon]:
                del self._reservations[key]
        retry, reserved = self.joins.reserve(f"viewers/{doc_id}")
        if retry is not None:
            if reserved:
                # The slot IS claimable later — the tenant debit stands
                # and covers the claim (which never re-debits).
                self._reservations[rkey] = now + retry
            elif self.tenant_joins is not None and tenant_id is not None:
                # Horizon-full refusal: nothing stayed debited on the
                # plane tier, so nothing may stay debited on the tenant
                # tier either (the retry pays both afresh).
                self.tenant_joins.refund(f"tenant/{tenant_id}")
            self.stats["join_nacks"] += 1
        return retry

    def join(self, doc_id: str, push: Callable[[Any], None],
             pending_probe: Callable[[], int] | None = None) -> dict:
        """Register one admitted viewer: fan-out subscriber with the
        shallow viewer queue bound, room membership, presence snapshot.
        Returns the viewer hello ({viewer_id, seq, viewers})."""
        vid = f"viewer-{self._next_vid}"
        self._next_vid += 1
        viewer = _Viewer(vid, doc_id, push, pending_probe)
        self._subscribe(viewer)
        self._viewers[vid] = viewer
        room = self._rooms.setdefault(doc_id, {})
        room[vid] = viewer
        self._room_arrays.pop(doc_id, None)
        self.stats["joins"] += 1
        self.metrics.counter("viewer.joins").inc()
        self._update_gauges()
        # Interest-sampled presence: the NEWCOMER gets a bounded sample
        # + the exact total; peers get a coalesced count update only
        # when the population moved materially (_maybe_announce).
        sample = [v.vid for _, v in zip(range(self.roster_sample),
                                        room.values())]
        push({"event": "viewer_presence", "doc": doc_id,
              "total": len(room), "sample": sample})
        self._maybe_announce(doc_id)
        return {"viewer_id": vid, "seq": self._last_seq.get(doc_id, 0),
                "viewers": len(room)}

    def _subscribe(self, viewer: _Viewer) -> None:
        fanout = self.fanout
        sub = fanout.connect()
        set_limit = getattr(fanout, "set_queue_limit", None)
        if set_limit is not None:  # duck-typed legacy fanouts lack it
            set_limit(sub, self.max_lag_frames)
        fanout.join(sub, self._room(viewer.doc_id))
        viewer.sub = sub

    def leave(self, vid: str) -> None:
        viewer = self._viewers.pop(vid, None)
        if viewer is None:
            return
        if viewer.sub is not None:
            self.fanout.disconnect(viewer.sub)
            viewer.sub = None
        room = self._rooms.get(viewer.doc_id)
        self._room_arrays.pop(viewer.doc_id, None)
        if room is not None:
            room.pop(vid, None)
            if not room:
                del self._rooms[viewer.doc_id]
                self._announced.pop(viewer.doc_id, None)
            else:
                self._maybe_announce(viewer.doc_id)
        self.stats["leaves"] += 1
        self._update_gauges()

    def resume(self, vid: str) -> dict:
        """Re-enter the live stream after a lag-drop: fresh subscriber,
        same viewer id. The caller re-gates through :meth:`admit_join`
        first (a resync storm is a join storm). Returns the hello shape
        (the seq the live stream resumes from; the gap up to it is the
        client's snapshot + get_deltas catch-up)."""
        viewer = self._viewers.get(vid)
        if viewer is None:
            raise KeyError(f"unknown viewer {vid!r}")
        if viewer.sub is None:
            self._subscribe(viewer)
            self._rooms.setdefault(viewer.doc_id, {})[vid] = viewer
            self._room_arrays.pop(viewer.doc_id, None)
            self.stats["resumes"] += 1
            self.metrics.counter("viewer.resumes").inc()
            self._update_gauges()
        return {"viewer_id": vid,
                "seq": self._last_seq.get(viewer.doc_id, 0),
                "viewers": self.room_size(viewer.doc_id)}

    # -- broadcast (the serving-tick hop) --------------------------------------

    def publish_ticks(self, items: list) -> int:
        """One serving tick's viewer broadcasts: ``items`` is
        ``[(doc_id, n_seq, first, last, msn, count, words_bytes), ...]``
        (only docs the storm harvest found viewer rooms for). Each doc's
        frame is encoded ONCE; the whole batch goes down in one
        ``fanout_publish_batch`` native call; the room queues then drain
        to the member transports with lag-drop applied. Returns frames
        delivered."""
        pubs = []
        docs = []
        for doc_id, n_seq, first, last, msn, count, words in items:
            if not self._rooms.get(doc_id):
                continue
            body = encode_viewer_tick_body(doc_id, n_seq, first, last,
                                           msn, count, words)
            self.stats["tick_encodes"] += 1
            if last > self._last_seq.get(doc_id, 0):
                self._last_seq[doc_id] = last
            pubs.append((self._room(doc_id), body))
            docs.append(doc_id)
        if not pubs:
            return 0
        self.metrics.counter("viewer.tick_encodes").inc(len(pubs))
        fanout = self.fanout
        batch_pub = getattr(fanout, "publish_batch", None)
        if batch_pub is not None:
            batch_pub(pubs)
        else:
            for room, body in pubs:
                fanout.publish(room, body)
        return self._drain(docs)

    def publish_ops(self, doc_id: str, messages) -> int:
        """Per-op path (the JSON broadcaster lambda): the sequenced-op
        batch encodes once through the shared :class:`BroadcastBatch`
        body and fans out to the doc's viewer room. Returns frames
        delivered (0 with no viewers — and no encode either)."""
        if not self._rooms.get(doc_id):
            return 0
        last = max((m.sequence_number for m in messages), default=0)
        if last <= self._last_seq.get(doc_id, 0):
            return 0  # bus crash-replay: the room already saw this op
        if not isinstance(messages, BroadcastBatch):
            messages = BroadcastBatch(messages)
        body = encode_ops_event(messages)
        self.stats["ops_encodes"] += 1
        self._last_seq[doc_id] = last
        self.fanout.publish(self._room(doc_id), body)
        return self._drain([doc_id])

    def _drain(self, docs: list[str]) -> int:
        """Deliver the named rooms' queued frames to their member
        transports. A member whose fan-out subscriber was evicted (queue
        past its viewer bound) or whose transport probe reports a deep
        outbox is LAG-DROPPED: removed from the room, handed a resync
        directive, tick untouched. Big rooms drain through
        ``fanout_poll_batch`` — FFI cost O(1) per room pass, however
        many viewers the room holds."""
        fanout = self.fanout
        batch_poll = getattr(fanout, "poll_batch", None)
        delivered = 0
        drained_bytes = 0
        for doc_id in docs:
            room = self._rooms.get(doc_id)
            if not room:
                continue
            # Transport-probe lag check first (Python-side signal the
            # fan-out cannot see); probes are rare, the attr check isn't.
            for viewer in [v for v in room.values()
                           if v.pending_probe is not None
                           and v.sub is not None
                           and v.pending_probe()
                           > self.transport_lag_frames]:
                self._lag_drop(viewer, "transport-backlog")
            if not room:
                continue
            if batch_poll is None:
                d, b = self._drain_room_single(room)
            else:
                d, b = self._drain_room_batched(doc_id, room, batch_poll)
            delivered += d
            drained_bytes += b
        if delivered:
            self.stats["delivered_frames"] += delivered
            self.stats["broadcast_bytes"] += drained_bytes
            self.metrics.counter("viewer.delivered_frames").inc(delivered)
            self.metrics.counter("viewer.broadcast_bytes").inc(
                drained_bytes)
        return delivered

    def _drain_room_single(self, room: dict) -> tuple[int, int]:
        """Per-subscriber drain for duck-typed fan-outs without the
        batch surface."""
        fanout = self.fanout
        delivered = drained_bytes = 0
        for viewer in list(room.values()):
            sub = viewer.sub
            if sub is None:
                continue
            if fanout.was_evicted(sub):
                self._lag_drop(viewer, "fanout-backlog")
                continue
            while (payload := fanout.poll(sub)) is not None:
                try:
                    viewer.push(RawBody(payload))
                except Exception:
                    self._lag_drop(viewer, "transport-dead")
                    break
                viewer.delivered += 1
                delivered += 1
                drained_bytes += len(payload)
        return delivered, drained_bytes

    def _drain_room_batched(self, doc_id: str, room: dict,
                            batch_poll) -> tuple[int, int]:
        import numpy as np

        entry = self._room_arrays.get(doc_id)
        if entry is None:
            members = [v for v in room.values() if v.sub is not None]
            subs = np.array([v.sub for v in members], np.int64)
            self._room_arrays[doc_id] = entry = (members, subs)
        members, subs = entry
        if not members:
            return 0, 0
        delivered = drained_bytes = 0
        dead: list[_Viewer] = []   # ordered for the lag-drop pass below
        dead_set: set[int] = set()  # O(1) membership by viewer identity
        while True:
            buf, lens = batch_poll(subs)
            lens_l = lens.tolist()
            off = 0
            any_frame = False
            for i, viewer in enumerate(members):
                length = lens_l[i]
                if length < 0:
                    if length == -2 and viewer.sub is not None \
                            and id(viewer) not in dead_set:
                        dead.append(viewer)  # evicted under the bound
                        dead_set.add(id(viewer))
                    continue
                any_frame = True
                payload = RawBody(buf[off:off + length])
                off += length
                if id(viewer) in dead_set:
                    continue  # popped alongside peers; viewer is gone
                try:
                    viewer.push(payload)
                except Exception:
                    dead.append(viewer)
                    dead_set.add(id(viewer))
                    continue
                viewer.delivered += 1
                delivered += 1
                drained_bytes += length
            if not any_frame:
                break
        for viewer in dead:
            self._lag_drop(viewer, "fanout-backlog")
        return delivered, drained_bytes

    def drain_all(self) -> int:
        """Idle-loop drain (bridge pump / operator tick): flush every
        room's queued frames — viewers on slow transports keep receiving
        between ticks."""
        return self._drain(list(self._rooms))

    def _lag_drop(self, viewer: _Viewer, reason: str,
                  moved_to: str | None = None) -> None:
        """Drop one slow viewer out of the live stream: its queue is
        abandoned (the fan-out already evicted it, or we disconnect it
        here), a ``viewer_resync`` directive tells the client to catch
        up via snapshot + get_deltas — the round-12 cold-read path, so a
        doc that went cold meanwhile still serves the gap from its
        cold-head tick index — and ``viewer_resume`` re-enters the
        stream. The serving tick never waits. ``moved_to`` (live
        migration re-home) names the doc's new owning host: the client
        resumes THERE after the catch-up."""
        if viewer.sub is not None:
            self.fanout.disconnect(viewer.sub)
            viewer.sub = None
        room = self._rooms.get(viewer.doc_id)
        self._room_arrays.pop(viewer.doc_id, None)
        if room is not None:
            room.pop(viewer.vid, None)
            if not room:
                self._rooms.pop(viewer.doc_id, None)
                self._announced.pop(viewer.doc_id, None)
        viewer.lag_drops += 1
        self.stats["lag_drops"] += 1
        self.metrics.counter("viewer.lag_drops").inc()
        directive = {"event": "viewer_resync", "doc": viewer.doc_id,
                     "seq": self._last_seq.get(viewer.doc_id, 0),
                     "reason": reason}
        if moved_to is not None:
            directive["moved_to"] = moved_to
        try:
            viewer.push(directive)
        except Exception:
            pass  # transport already dead; the session teardown cleans up
        self._update_gauges()

    def resync_room(self, doc_id: str, reason: str = "moved",
                    moved_to: str | None = None) -> int:
        """Re-home one doc's WHOLE viewer room (live migration): every
        member is dropped to the resync dance with the new owner in the
        directive — catch-up rides the cold-read path (the migrated
        doc's cold head serves the gap without hydrating here), the
        resume lands on ``moved_to``. Returns viewers re-homed."""
        room = self._rooms.get(doc_id)
        if not room:
            return 0
        members = list(room.values())
        for viewer in members:
            self._lag_drop(viewer, reason, moved_to=moved_to)
        self.stats["rehomes"] = self.stats.get("rehomes", 0) \
            + len(members)
        self.metrics.counter("viewer.rehomes").inc(len(members))
        return len(members)

    def spread_room(self, doc_id: str, labels: list[str],
                    reason: str = "moved") -> dict[str, int]:
        """Re-home one doc's room ACROSS hosts (the read-replica tier's
        audience spread): each member is lag-dropped with a
        hash-assigned label in its directive, so the room's audience
        lands spread over ``labels`` instead of stampeding one host.
        A member that re-resolves through the replica directory instead
        may hash to a different label — either way it lands on a
        replica serving the doc. Returns viewers re-homed per label."""
        import zlib
        room = self._rooms.get(doc_id)
        if not room or not labels:
            return {}
        members = list(room.values())
        counts: dict[str, int] = {}
        for viewer in members:
            label = labels[zlib.crc32(viewer.vid.encode())
                           % len(labels)]
            self._lag_drop(viewer, reason, moved_to=label)
            counts[label] = counts.get(label, 0) + 1
        self.stats["rehomes"] = self.stats.get("rehomes", 0) \
            + len(members)
        self.metrics.counter("viewer.rehomes").inc(len(members))
        return counts

    # -- presence --------------------------------------------------------------

    def _maybe_announce(self, doc_id: str) -> None:
        """Coalesced presence: publish ONE count-update frame to the room
        when the population moved ≥ 1/8 since the last announce (O(log)
        announcements per audience doubling — never one per join)."""
        room = self._rooms.get(doc_id)
        if not room:
            return
        total = len(room)
        last = self._announced.get(doc_id, 0)
        if last and abs(total - last) < max(1, last // 8):
            return
        self._announced[doc_id] = total
        body = RawBody(encode_body({"event": "viewer_presence",
                                    "doc": doc_id, "total": total}))
        self.stats["presence_updates"] += 1
        self.metrics.counter("viewer.presence_updates").inc()
        self.fanout.publish(self._room(doc_id), body)

    # -- observability ---------------------------------------------------------

    def _update_gauges(self) -> None:
        self.metrics.gauge("viewer.rooms").set(len(self._rooms))
        self.metrics.gauge("viewer.viewers").set(len(self._viewers))


__all__ = ["ViewerPlane", "ViewerConnection"]
