"""Durable storage: file-backed bus, state store and snapshot store.

Reference parity — the three durability legs of the reference server:
  * Kafka partition logs (services-ordering-*, config.json:26-38)
    → :class:`DurableMessageBus` — one CRC-framed C++ op log per
    topic-partition (native/oplog.cpp), offsets journaled.
  * MongoDB lambda checkpoints + scriptorium op log
    (scriptorium/lambda.ts:95, deli/checkpointContext.ts)
    → :class:`FileStateStore` — a journaled key→document store.
  * gitrest content-addressed snapshot storage over libgit2
    (server/gitrest/src/utils.ts:9)
    → :class:`GitSnapshotStore` — sha256-addressed chunked blobs with a
    per-document head ref.

All three survive process death: a service rebuilt over the same directory
resumes from checkpoints exactly as a routerlicious pod restart does.
Values serialize through the protocol wire codec (tagged dataclasses);
``RawOperation`` registers itself as an extension tag below.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import threading
import time
from pathlib import Path
from typing import Any, Callable

from ..native import OpLog
from ..protocol.codec import from_wire, register_codec, to_wire
from ..protocol.messages import MessageType
from ..utils import faults
from .bus import BusMessage, MessageBus, Topic
from .sequencer import RawOperation

# -- RawOperation over the wire/journal ---------------------------------------

register_codec(
    "raw", RawOperation,
    lambda op: {f.name: getattr(op, f.name)
                for f in dataclasses.fields(RawOperation)},
    lambda body: RawOperation(**{
        **body,
        "type": MessageType(body["type"]),
        "traces": tuple(body.get("traces", ())),
    }))


def _dump(value: Any) -> bytes:
    return json.dumps(to_wire(value), separators=(",", ":")).encode()


def _load(data: bytes) -> Any:
    return from_wire(json.loads(data.decode()))


# -- group-commit WAL writer --------------------------------------------------


class WalDegradedError(RuntimeError):
    """A durability barrier was requested while the WAL's circuit breaker
    is open (sustained fsync failure) — the caller must degrade to
    read-only serving instead of blocking on a disk that is not coming
    back this instant."""


class CircuitBreaker:
    """Closed → open → half-open breaker for the WAL writer (the pattern
    every storage-backed service front: shed fast while the disk is sick,
    probe periodically, heal without a restart).

    * **closed** — healthy; failures count toward ``failure_threshold``.
    * **open** — shedding; for ``cooldown_s`` after the last failure all
      probes are refused, then ONE probe is allowed (half-open).
    * **half-open** — the single in-flight probe decides: success closes
      the breaker (and resets the count), failure re-opens it for another
      cooldown.

    Thread-safe via a single mutex; every method is O(1).
    """

    def __init__(self, failure_threshold: int = 1,
                 cooldown_s: float = 0.25,
                 clock: Callable[[], float] = None) -> None:
        import time as _time
        self.failure_threshold = max(1, failure_threshold)
        self.cooldown_s = cooldown_s
        self._clock = clock if clock is not None else _time.monotonic
        self._mutex = threading.Lock()
        self._failures = 0
        self._opened_at: float | None = None
        self._probing = False
        self.stats = {"opens": 0, "probes": 0, "closes": 0}

    @property
    def state(self) -> str:
        with self._mutex:
            if self._opened_at is None:
                return "closed"
            return "half-open" if self._probing else "open"

    @property
    def is_open(self) -> bool:
        with self._mutex:
            return self._opened_at is not None

    def record_failure(self) -> None:
        with self._mutex:
            self._failures += 1
            self._probing = False
            if self._failures >= self.failure_threshold:
                if self._opened_at is None:
                    self.stats["opens"] += 1
                self._opened_at = self._clock()

    def record_success(self) -> None:
        with self._mutex:
            if self._opened_at is not None:
                self.stats["closes"] += 1
            self._failures = 0
            self._opened_at = None
            self._probing = False

    def allow(self) -> bool:
        """May the protected operation run now? True while closed; while
        open, True exactly once per elapsed cooldown (the half-open
        probe)."""
        with self._mutex:
            if self._opened_at is None:
                return True
            if self._probing:
                return False
            if self._clock() - self._opened_at >= self.cooldown_s:
                self._probing = True
                self.stats["probes"] += 1
                return True
            return False


def rewrite_oplog_records(log, path, transform):
    """Rewrite an :class:`OpLog` record by record — ``transform(idx,
    data)`` returns replacement bytes or None to keep — preserving
    record count and indices, published atomically (tmp file + rename,
    so a kill mid-rewrite keeps the original). Returns ``(fresh_log,
    changed)``; the caller owns locking and adopts the fresh handle.
    Shared by :meth:`GroupCommitLog.rewrite_records` and the storm
    controller's plain-OpLog spill trim — one copy of the
    crash-safety-critical publish sequence."""
    path = Path(path)
    tmp = path.with_suffix(".compact")
    tmp.unlink(missing_ok=True)
    fresh = OpLog(tmp)
    changed = 0
    for i in range(len(log)):
        data = bytes(log.read(i))
        new = transform(i, data)
        if new is not None and new != data:
            changed += 1
            data = new
        fresh.append(data)
    fresh.sync()
    fresh.close()
    log.close()
    tmp.replace(path)
    return OpLog(path), changed


class GroupCommitLog:
    """Async group-commit writer over a CRC-framed :class:`OpLog`.

    The WAL durability shape of every real ordering service (Kafka's
    ``log.flush`` batching, Mongo's journal group commit): ``append``
    enqueues on a bounded queue and returns the record index immediately;
    a background writer drains the WHOLE queue, appends every queued
    record to the CRC-framed log, fsyncs ONCE, then advances the durable
    watermark and fires the completion callbacks. The caller's hot path
    pays a queue put — never a serialize-join, never an fsync.

    Crash contract (what the chaos harness proves):

    * records below :attr:`durable_len` survive a kill at ANY point —
      the file format is the OpLog's ``[u32 len][u32 crc32][payload]``
      framing, so a torn batch truncates to the last intact record on
      reopen exactly like every other log in the tier;
    * records at-or-above the watermark may be lost — which is why the
      storm path withholds acks until the watermark passes the tick.

    Reads are index-transparent: a record still queued serves from the
    in-flight buffer, so catch-up readers never block on the fsync
    cadence. Payloads may be passed as a list of buffers; the join runs
    on the writer thread (the ~MB-per-tick memcpy leaves the hot path).
    Completion callbacks run ON THE WRITER THREAD — keep them tiny and
    thread-safe (the storm controller only polls the watermark).
    """

    def __init__(self, path: str | os.PathLike, max_queue: int = 256,
                 fsync: bool = True,
                 breaker: CircuitBreaker | None = None,
                 commit_latency_s: float = 0.0) -> None:
        self._log = OpLog(path)
        self._path = Path(path)  # rewrite_records replace target
        self._fsync = fsync
        # Modeled additional commit latency per fsync BATCH (writer
        # thread only, after the real fsync): benches use it to put the
        # WAL in the replicated-log regime (quorum append / networked
        # disk) where a host's commit round trip — not its CPU — bounds
        # its serving rate. 0 (default, production) = local disk only.
        self._commit_latency_s = max(0.0, commit_latency_s)
        # Serializes ALL OpLog access: neither backend is thread-safe
        # (the Python one shares a single seek position between read and
        # append; the native one grows its index vector unsynchronized),
        # so reads from the serving thread must never interleave with the
        # writer thread's append/fsync batch. Separate from _lock so
        # append() callers never block behind an in-flight fsync.
        self._io = threading.Lock()
        self._lock = threading.Condition()
        self._queued: dict[int, list[bytes]] = {}
        self._callbacks: dict[int, Callable[[int], None]] = {}
        self._next = len(self._log)
        self._durable = self._next  # reopened records are durable history
        # Records written to the OS file but not yet fsynced: a retry
        # after a failed fsync must never re-append them (duplicate
        # records would shift every later index).
        self._appended_next = self._next
        self._max_queue = max(1, max_queue)
        self._error: BaseException | None = None
        # Fsync-failure circuit breaker: a failed batch stays queued and
        # the writer RETRIES on the breaker's half-open cadence instead
        # of dying — the WAL degrades and heals, it does not brick.
        # Callers poll `breaker.is_open` to enter/leave read-only mode.
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        #: TERMINAL writer death (non-I/O failure; the breaker stays
        #: open and will never heal) — callers distinguish this from a
        #: sick-disk outage and stop telling clients to retry.
        self.failed = False
        #: Log-shipping seam (server/replication.py): called ON THE
        #: WRITER THREAD after the local fsync with the batch's
        #: ``[(index, record_bytes), ...]`` — the exact bytes that just
        #: became locally durable, in index order. A replication plane
        #: hooks this to ship the batch to followers before the durable
        #: watermark advances; shipping failures must never kill the
        #: writer (the plane resyncs lagging followers from the log), so
        #: exceptions are swallowed here and surfaced by the plane's own
        #: health gauges.
        self.on_batch_durable: Callable[[list], None] | None = None
        self._stop = False
        self._thread = threading.Thread(target=self._writer_loop,
                                        name="group-commit-wal", daemon=True)
        self._thread.start()

    def __len__(self) -> int:
        with self._lock:
            return self._next

    @property
    def durable_len(self) -> int:
        """Records fsynced to disk — the acknowledged-durability
        watermark (everything below survives a crash)."""
        with self._lock:
            return self._durable

    def append(self, data: bytes | bytearray | memoryview | list,
               on_durable: Callable[[int], None] | None = None) -> int:
        """Enqueue one record; returns its index immediately. Blocks only
        when the bounded queue is full (backpressure, not unbounded RAM).
        With the breaker OPEN a full queue raises WalDegradedError
        instead of waiting — the writer is in its probe cycle and space
        is not freeing on any bounded schedule; the caller must shed."""
        parts = list(data) if isinstance(data, list) else [data]
        with self._lock:
            while len(self._queued) >= self._max_queue:
                if self.breaker.is_open:
                    raise WalDegradedError(
                        "WAL queue full while the fsync breaker is open"
                    ) from self._error
                self._lock.wait(timeout=1.0)
            idx = self._next
            self._next += 1
            self._queued[idx] = parts
            if on_durable is not None:
                self._callbacks[idx] = on_durable
            self._lock.notify_all()
        return idx

    def read(self, index: int) -> bytes:
        with self._lock:
            parts = self._queued.get(index)
            if parts is not None:
                return b"".join(bytes(p) for p in parts)
        with self._io:
            return self._log.read(index)

    def sync(self) -> None:
        """Barrier: returns once every record appended so far is durable.
        Raises :class:`WalDegradedError` (without waiting out the outage)
        when the breaker is open — durability is not coming on a bounded
        schedule, and callers holding the serving thread must degrade to
        read-only rather than block on it."""
        with self._lock:
            target = self._next
            while self._durable < target:
                if self.breaker.is_open:
                    raise WalDegradedError(
                        "WAL fsync breaker is open; durability barrier "
                        "unavailable") from self._error
                self._lock.wait(timeout=1.0)

    def rewrite_records(self, transform: Callable[[int, bytes],
                                                  bytes | None]) -> int:
        """Rewrite the log in place, record by record: ``transform(idx,
        data)`` returns replacement bytes or None to keep. Record COUNT
        and indices are preserved — this exists for the history plane's
        tail trim, which shrinks superseded tick blobs to fillers
        without moving any WAL position. Barriers on full durability
        first, requires an empty queue (call between serving rounds,
        never on the hot path), and publishes atomically (tmp file +
        rename), so a kill mid-rewrite keeps the original log intact.
        Returns the number of records replaced."""
        self.sync()
        with self._lock:
            assert not self._queued, \
                "rewrite_records with queued (unfsynced) appends"
        with self._io:
            self._log, changed = rewrite_oplog_records(
                self._log, self._path, transform)
        return changed

    def close(self) -> None:
        with self._lock:
            self._stop = True
            self._lock.notify_all()
        self._thread.join(timeout=10)
        self._log.close()

    def _writer_loop(self) -> None:
        while True:
            with self._lock:
                while not self._queued and not self._stop:
                    self._lock.wait(timeout=1.0)
                if self._stop and not self._queued:
                    return
                batch = sorted(self._queued)
                parts_of = {i: self._queued[i] for i in batch}
            if not self.breaker.allow():
                # Open breaker, cooldown not yet elapsed: hold the batch
                # (records stay queued and readable) and poll again.
                with self._lock:
                    if self._stop:
                        return  # close() during an outage abandons the tail
                    self._lock.wait(timeout=min(0.05,
                                                self.breaker.cooldown_s))
                continue
            try:
                with self._io:
                    for idx in batch:
                        if idx < self._appended_next:
                            continue  # appended before a failed fsync
                        data = b"".join(bytes(p) for p in parts_of[idx])
                        got = self._log.append(data)
                        # Advance BEFORE asserting: the record is on the
                        # file either way, and a retry after the assert
                        # must never append it twice.
                        self._appended_next = max(self._appended_next,
                                                  got + 1)
                        assert got == idx, (got, idx)
                    faults.crashpoint("wal.pre_fsync")
                    if self._fsync:
                        faults.failpoint("wal.fsync")
                        self._log.sync()
                if self._fsync and self._commit_latency_s:
                    # Modeled commit round trip OUTSIDE the io lock:
                    # it delays the durable watermark (as a replicated
                    # log's quorum ack would), never reads of records
                    # already appended to the local file.
                    time.sleep(self._commit_latency_s)
                faults.crashpoint("wal.post_fsync")
                ship = self.on_batch_durable
                if ship is not None:
                    # Ship the locally-durable batch BEFORE the watermark
                    # advances: a synchronous plane returns only once its
                    # quorum acked, so durable_len then implies
                    # replicated too. An async/failed ship leaves the
                    # follower behind; the plane's resync path re-ships
                    # the tail from the log — never from here.
                    try:
                        ship([(idx,
                               b"".join(bytes(p) for p in parts_of[idx]))
                              for idx in batch
                              if idx in parts_of])
                    except Exception:
                        pass  # plane reports its own health; writer lives
            except OSError as err:
                # Transient I/O (the breaker's whole domain): keep the
                # records queued and retry on the half-open cadence.
                # Callers observe breaker.is_open and shed.
                with self._lock:
                    self._error = err
                    self._lock.notify_all()
                self.breaker.record_failure()
                continue
            except BaseException as err:
                # Deterministic / non-I/O failure (index skew, bad
                # payload types): retrying would loop forever or
                # duplicate records. The writer exits; the breaker is
                # forced open permanently so sync()/append() surface
                # WalDegradedError instead of hanging.
                with self._lock:
                    self._error = err
                    self._lock.notify_all()
                self.failed = True
                while not self.breaker.is_open:
                    self.breaker.record_failure()
                return
            self.breaker.record_success()
            with self._lock:
                self._error = None
                for idx in batch:
                    del self._queued[idx]
                self._durable = batch[-1] + 1
                callbacks = [(i, self._callbacks.pop(i))
                             for i in batch if i in self._callbacks]
                self._lock.notify_all()
            for idx, cb in callbacks:
                cb(idx)


# -- durable bus --------------------------------------------------------------


class _DurablePartition:
    """In-memory view append-through to an op log file."""

    def __init__(self, path: Path) -> None:
        self._oplog = OpLog(path)
        self.log: list[BusMessage] = []
        #: Offset of ``log[0]`` (bus retention trims the consumed
        #: in-memory prefix; the on-disk oplog keeps full history).
        self.base = 0
        # Appends since the last fsync: the offset journal must never
        # claim a message consumed that the data log could still lose, so
        # commit() group-syncs dirty partitions first (one fsync covers
        # every append of the batch — Kafka's log.flush-before-offsets).
        self.dirty = False
        for i in range(len(self._oplog)):
            key, value = _load(self._oplog.read(i))
            self.log.append(BusMessage(i, key, value))

    def append(self, key: str, value: Any) -> int:
        offset = self.base + len(self.log)
        data = _dump([key, value])
        self._oplog.append(data)
        self.dirty = True
        # Keep the codec-decoded copy in memory so consumers see identical
        # shapes (tuples→lists etc.) before and after a restart replay.
        self.log.append(BusMessage(offset, key, _load(data)[1]))
        return offset

    def trim(self, upto: int) -> int:
        """In-memory retention trim (bus.MessageBus contract): the
        durable oplog is untouched — a restart replays full history and
        re-trims as groups re-commit."""
        cut = min(max(0, upto - self.base), len(self.log))
        if cut:
            del self.log[:cut]
            self.base += cut
        return cut

    def sync_if_dirty(self) -> None:
        if self.dirty:
            self._oplog.sync()
            self.dirty = False

    def close(self) -> None:
        self._oplog.close()


class _DurableTopic(Topic):
    def __init__(self, name: str, num_partitions: int, root: Path) -> None:
        self.name = name
        self.partitions = [
            _DurablePartition(root / f"{name}-{p}.log")
            for p in range(num_partitions)]


class DurableMessageBus(MessageBus):
    """MessageBus whose partitions and consumer offsets live on disk.

    Reopening the same directory restores every topic log and committed
    offset — the consumer-group replay semantics lambdas rely on
    (kafka-service/checkpointManager.ts:24).
    """

    OFFSET_COMPACT_THRESHOLD = 4096

    def __init__(self, root: str | os.PathLike,
                 retention_messages: int | None = None) -> None:
        super().__init__(retention_messages=retention_messages)
        self._root = Path(root)
        self._root.mkdir(parents=True, exist_ok=True)
        # Topic metadata journal: partition counts are fixed at creation
        # (the Kafka rule) — reopening always uses the recorded count, so a
        # caller passing a different num_partitions can never orphan logs
        # or remap keys.
        self._meta_log = OpLog(self._root / "topics.log")
        self._topic_partitions: dict[str, int] = {}
        for i in range(len(self._meta_log)):
            name, count = _load(self._meta_log.read(i))
            self._topic_partitions[name] = count
        self._offset_path = self._root / "offsets.log"
        self._offset_log = OpLog(self._offset_path)
        for i in range(len(self._offset_log)):
            topic, group, partition, nxt = _load(self._offset_log.read(i))
            self._offsets[(topic, group, partition)] = nxt
            # Groups with durable offsets pin the retention floor from
            # the moment the bus reopens — a group whose Consumer
            # re-attaches LATE must not find its position trimmed out
            # from under it by an earlier group's commits.
            self.register_group(topic, group)
        self._offset_records = len(self._offset_log)

    def create_topic(self, name: str, num_partitions: int = 4) -> Topic:
        if name not in self._topics:
            recorded = self._topic_partitions.get(name)
            if recorded is None:
                self._meta_log.append(_dump([name, num_partitions]))
                self._topic_partitions[name] = num_partitions
                recorded = num_partitions
            self._topics[name] = _DurableTopic(name, recorded, self._root)
        return self._topics[name]

    def commit(self, topic: str, group: str, partition: int,
               next_offset: int) -> None:
        if self._offsets.get((topic, group, partition)) == next_offset:
            return
        # Durability ordering: data BEFORE offsets. A committed offset is
        # a claim that everything below it was consumed; if the partition
        # log lost those records to a crash, replay-from-offset would skip
        # ops no lambda ever saw. Group commit: the whole batch of appends
        # since the last commit shares this one fsync.
        t = self._topics.get(topic)
        if t is not None and partition < len(t.partitions):
            t.partitions[partition].sync_if_dirty()
        super().commit(topic, group, partition, next_offset)
        self._offset_log.append(_dump([topic, group, partition, next_offset]))
        self._offset_records += 1
        if self._offset_records > max(self.OFFSET_COMPACT_THRESHOLD,
                                      4 * len(self._offsets)):
            self._compact_offsets()

    def _compact_offsets(self) -> None:
        self._offset_log.close()
        tmp = self._offset_path.with_suffix(".compact")
        tmp.unlink(missing_ok=True)
        fresh = OpLog(tmp)
        for (topic, group, partition), nxt in sorted(self._offsets.items()):
            fresh.append(_dump([topic, group, partition, nxt]))
        fresh.sync()
        fresh.close()
        tmp.replace(self._offset_path)
        self._offset_log = OpLog(self._offset_path)
        self._offset_records = len(self._offset_log)

    def close(self) -> None:
        self._meta_log.close()
        self._offset_log.close()
        for topic in self._topics.values():
            for part in topic.partitions:
                part.close()


# -- durable state store ------------------------------------------------------


class FileStateStore:
    """Journaled key→document store (same duck-typed surface as the
    in-memory StateStore). Every put/append is one journal record; open
    replays the journal into memory. ``compact()`` rewrites the journal as
    one snapshot record per key (the Mongo-compaction analog)."""

    COMPACT_THRESHOLD = 8192

    def __init__(self, root: str | os.PathLike) -> None:
        self._root = Path(root)
        self._root.mkdir(parents=True, exist_ok=True)
        self._path = self._root / "state.log"
        self._journal = OpLog(self._path)
        self._dirty = False  # appends since the last sync (group commit)
        self._data: dict[str, Any] = {}
        for i in range(len(self._journal)):
            kind, key, value = _load(self._journal.read(i))
            if kind == "put":
                self._data[key] = value
            else:
                self._data.setdefault(key, []).extend(value)
        self._records = len(self._journal)

    def get(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)

    def put(self, key: str, value: Any) -> None:
        data = _dump(["put", key, value])
        self._journal.append(data)
        # Keep the codec-decoded copy so in-memory state is identical to a
        # post-restart replay (tuples become lists etc.) — no dual-shape
        # bugs between first run and recovery. One serialization pass: the
        # journal bytes are the source.
        self._data[key] = _load(data)[2]
        self._bump()

    def append(self, key: str, items: list) -> None:
        data = _dump(["append", key, items])
        self._journal.append(data)
        self._data.setdefault(key, []).extend(_load(data)[2])
        self._bump()

    def _bump(self) -> None:
        self._records += 1
        self._dirty = True
        if self._records > max(self.COMPACT_THRESHOLD, 8 * len(self._data)):
            self.compact()

    def keys(self, prefix: str = "") -> list[str]:
        return sorted(k for k in self._data if k.startswith(prefix))

    def sync(self) -> None:
        """Group commit: one fsync covers every record since the last
        (no-op when nothing was written — callers sync per checkpoint)."""
        if self._dirty:
            self._journal.sync()
            self._dirty = False

    def compact(self) -> None:
        self._journal.close()
        tmp = self._path.with_suffix(".compact")
        tmp.unlink(missing_ok=True)
        fresh = OpLog(tmp)
        for key in self.keys():
            fresh.append(_dump(["put", key, self._data[key]]))
        fresh.sync()
        fresh.close()
        tmp.replace(self._path)
        self._journal = OpLog(self._path)
        self._records = len(self._journal)
        self._dirty = False  # the compacted journal was synced pre-publish

    def close(self) -> None:
        self._journal.close()


# -- content-addressed snapshot store -----------------------------------------

CHUNK_BYTES = 64 * 1024


class GitSnapshotStore:
    """gitrest analog: snapshots as sha256-addressed chunked blobs.

    A snapshot serializes to canonical JSON, splits into CHUNK_BYTES
    blobs (each stored once under its content hash — structural sharing
    across summaries for free, like git blobs), and a tree object lists
    the chunk hashes. Heads are per-document ref files. Implements the
    snapshot-backend surface RouterliciousService uses (upload / get /
    head / set_head).

    GC: every upload journals a refcount increment for its tree + chunk
    objects (``refcounts.log``, fsynced before the handle is returned —
    an object can never be reachable without its count being durable);
    :meth:`release` decrements a superseded snapshot's references and
    deletes objects whose count reaches zero. Content sharing stays safe
    across documents (a chunk two cold docs dedup into survives until
    BOTH release), and objects that predate the journal (legacy stores)
    are never deleted — an untracked sha is pinned, not collectable.
    The residency manager releases each cold doc's superseded head on
    eviction (head flip), closing the round-12 leftover: a churned cold
    doc's disk cost stays one snapshot, not one per eviction.
    """

    def __init__(self, root: str | os.PathLike) -> None:
        self._root = Path(root)
        (self._root / "objects").mkdir(parents=True, exist_ok=True)
        (self._root / "refs").mkdir(parents=True, exist_ok=True)
        # sha -> reference count, rebuilt from the journal ("+"/"-"
        # records of sha lists). Missing shas are LEGACY (pre-journal)
        # objects: pinned forever rather than risk deleting a blob an
        # untracked head still needs.
        self._rc_path = self._root / "refcounts.log"
        self._rc_log = OpLog(self._rc_path)
        self._rc: dict[str, int] = {}
        for i in range(len(self._rc_log)):
            sign, shas = _load(self._rc_log.read(i))
            delta = 1 if sign == "+" else -1
            for sha in shas:
                self._rc[sha] = self._rc.get(sha, 0) + delta
        self._rc_records = len(self._rc_log)

    RC_COMPACT_THRESHOLD = 8192

    def _journal_refs(self, sign: str, shas: list[str]) -> None:
        self._rc_log.append(_dump([sign, shas]))
        self._rc_log.sync()
        delta = 1 if sign == "+" else -1
        for sha in shas:
            self._rc[sha] = self._rc.get(sha, 0) + delta
        self._rc_records += 1
        if self._rc_records > max(self.RC_COMPACT_THRESHOLD,
                                  8 * len(self._rc)):
            self._compact_refs()

    def _compact_refs(self) -> None:
        self._rc_log.close()
        tmp = self._rc_path.with_suffix(".compact")
        tmp.unlink(missing_ok=True)
        fresh = OpLog(tmp)
        live = sorted(sha for sha, n in self._rc.items() if n > 0)
        for sha in live:
            fresh.append(_dump(["+", [sha] * self._rc[sha]]))
        fresh.sync()
        fresh.close()
        tmp.replace(self._rc_path)
        self._rc = {sha: self._rc[sha] for sha in live}
        self._rc_log = OpLog(self._rc_path)
        self._rc_records = len(self._rc_log)

    # -- object plumbing ------------------------------------------------------

    _SHA_RE = re.compile(r"[0-9a-f]{64}")

    def _object_path(self, sha: str) -> Path:
        # Handles arrive from clients (SUMMARIZE op contents) — a malformed
        # one must never touch the filesystem (path traversal).
        if not isinstance(sha, str) or not self._SHA_RE.fullmatch(sha):
            raise KeyError(f"invalid object id {sha!r}")
        return self._root / "objects" / sha[:2] / sha[2:]

    def put_object(self, data: bytes) -> str:
        sha = hashlib.sha256(data).hexdigest()
        path = self._object_path(sha)
        if not path.exists():
            path.parent.mkdir(exist_ok=True)
            tmp = path.with_suffix(".tmp")
            tmp.write_bytes(data)
            tmp.replace(path)  # atomic publish; dedup by content
        return sha

    def get_object(self, sha: str) -> bytes:
        return self._object_path(sha).read_bytes()

    # -- snapshot surface -----------------------------------------------------

    def upload(self, doc_id: str, snapshot: dict, put_object=None) -> str:
        """``put_object`` lets a caching front (historian) write through
        itself so freshly-uploaded chunks are served hot."""
        put = put_object if put_object is not None else self.put_object
        body = json.dumps(to_wire(snapshot), sort_keys=True,
                          separators=(",", ":")).encode()
        chunks = []
        for i in range(0, max(len(body), 1), CHUNK_BYTES):
            chunks.append(put(body[i:i + CHUNK_BYTES]))
            # A kill here leaves orphan chunk objects but no reachable
            # tree — the head ref still points at the previous snapshot.
            faults.crashpoint("snapshot.mid_upload")
        tree = json.dumps({"chunks": chunks, "doc": doc_id}).encode()
        handle = put(tree)
        # Refcounts durable BEFORE the handle escapes: a reachable
        # snapshot always has tracked references (a kill between journal
        # and caller leaves a +1 orphan — a leak, never an over-delete).
        # IDEMPOTENT re-upload of the doc's CURRENT head claims nothing
        # new (callers skip the matching release when the head doesn't
        # move) — journaling it would inflate the count one-sidedly and
        # make the snapshot undeletable forever.
        if self.head(doc_id) != handle:
            self._journal_refs("+", chunks + [handle])
        return handle

    def release(self, doc_id: str, handle: str) -> list[str]:
        """Release one SUPERSEDED snapshot's references (the caller just
        flipped ``doc_id``'s head ref off ``handle``); deletes objects
        whose refcount reaches zero. Refuses to release the current
        head. Returns the deleted object shas (caching fronts invalidate
        exactly these). Legacy (untracked) objects and chunks still
        shared by other snapshots survive."""
        if handle is None or self.head(doc_id) == handle:
            return []
        try:
            tree = json.loads(self.get_object(handle).decode())
            shas = list(tree.get("chunks", ())) + [handle]
        except (OSError, ValueError, KeyError):
            return []  # already gone / unreadable: nothing to release
        # Decide deletability from the PRE-decrement counts (the journal
        # append below may trigger a compaction that drops zeroed shas
        # from the map — reading counts afterwards would mistake them
        # for legacy-pinned objects and leak forever): a sha whose whole
        # remaining tracked count is the occurrences THIS release drops
        # reaches zero. Legacy objects (absent from the journal) and
        # over-released shas (count would go negative) stay pinned.
        occurrences: dict[str, int] = {}
        for sha in shas:
            occurrences[sha] = occurrences.get(sha, 0) + 1
        deletable = [sha for sha, occ in occurrences.items()
                     if self._rc.get(sha) == occ]
        # Journal the decrement BEFORE deleting files: a kill in between
        # orphans files (a leak the next release of the same sha cannot
        # double-free — its count is already zero and it skips).
        self._journal_refs("-", shas)
        deleted: list[str] = []
        for sha in deletable:
            try:
                self._object_path(sha).unlink(missing_ok=True)
                deleted.append(sha)
            except (OSError, KeyError):
                pass
            self._rc.pop(sha, None)
        return deleted

    def get(self, doc_id: str, handle: str | None,
            read_object=None) -> dict | None:
        """``read_object`` lets a caching front substitute its cached
        reader; the tree/chunk format is parsed in exactly one place."""
        read = read_object if read_object is not None else self.get_object
        if handle is None:
            return None
        try:
            tree = json.loads(read(handle).decode())
            body = b"".join(read(c) for c in tree["chunks"])
        except (OSError, ValueError, KeyError, TypeError):
            return None
        return from_wire(json.loads(body.decode()))

    def _ref_path(self, doc_id: str) -> Path:
        safe = hashlib.sha256(doc_id.encode()).hexdigest()[:32]
        return self._root / "refs" / safe

    def head(self, doc_id: str) -> str | None:
        path = self._ref_path(doc_id)
        return path.read_text() if path.exists() else None

    def set_head(self, doc_id: str, handle: str) -> None:
        path = self._ref_path(doc_id)
        tmp = path.with_suffix(".tmp")
        tmp.write_text(handle)
        tmp.replace(path)
