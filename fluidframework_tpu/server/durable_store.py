"""Durable storage: file-backed bus, state store and snapshot store.

Reference parity — the three durability legs of the reference server:
  * Kafka partition logs (services-ordering-*, config.json:26-38)
    → :class:`DurableMessageBus` — one CRC-framed C++ op log per
    topic-partition (native/oplog.cpp), offsets journaled.
  * MongoDB lambda checkpoints + scriptorium op log
    (scriptorium/lambda.ts:95, deli/checkpointContext.ts)
    → :class:`FileStateStore` — a journaled key→document store.
  * gitrest content-addressed snapshot storage over libgit2
    (server/gitrest/src/utils.ts:9)
    → :class:`GitSnapshotStore` — sha256-addressed chunked blobs with a
    per-document head ref.

All three survive process death: a service rebuilt over the same directory
resumes from checkpoints exactly as a routerlicious pod restart does.
Values serialize through the protocol wire codec (tagged dataclasses);
``RawOperation`` registers itself as an extension tag below.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
from pathlib import Path
from typing import Any

from ..native import OpLog
from ..protocol.codec import from_wire, register_codec, to_wire
from ..protocol.messages import MessageType
from .bus import BusMessage, MessageBus, Topic
from .sequencer import RawOperation

# -- RawOperation over the wire/journal ---------------------------------------

register_codec(
    "raw", RawOperation,
    lambda op: {f.name: getattr(op, f.name)
                for f in dataclasses.fields(RawOperation)},
    lambda body: RawOperation(**{
        **body,
        "type": MessageType(body["type"]),
        "traces": tuple(body.get("traces", ())),
    }))


def _dump(value: Any) -> bytes:
    return json.dumps(to_wire(value), separators=(",", ":")).encode()


def _load(data: bytes) -> Any:
    return from_wire(json.loads(data.decode()))


# -- durable bus --------------------------------------------------------------


class _DurablePartition:
    """In-memory view append-through to an op log file."""

    def __init__(self, path: Path) -> None:
        self._oplog = OpLog(path)
        self.log: list[BusMessage] = []
        for i in range(len(self._oplog)):
            key, value = _load(self._oplog.read(i))
            self.log.append(BusMessage(i, key, value))

    def append(self, key: str, value: Any) -> int:
        offset = len(self.log)
        data = _dump([key, value])
        self._oplog.append(data)
        # Keep the codec-decoded copy in memory so consumers see identical
        # shapes (tuples→lists etc.) before and after a restart replay.
        self.log.append(BusMessage(offset, key, _load(data)[1]))
        return offset

    def close(self) -> None:
        self._oplog.close()


class _DurableTopic(Topic):
    def __init__(self, name: str, num_partitions: int, root: Path) -> None:
        self.name = name
        self.partitions = [
            _DurablePartition(root / f"{name}-{p}.log")
            for p in range(num_partitions)]


class DurableMessageBus(MessageBus):
    """MessageBus whose partitions and consumer offsets live on disk.

    Reopening the same directory restores every topic log and committed
    offset — the consumer-group replay semantics lambdas rely on
    (kafka-service/checkpointManager.ts:24).
    """

    OFFSET_COMPACT_THRESHOLD = 4096

    def __init__(self, root: str | os.PathLike) -> None:
        super().__init__()
        self._root = Path(root)
        self._root.mkdir(parents=True, exist_ok=True)
        # Topic metadata journal: partition counts are fixed at creation
        # (the Kafka rule) — reopening always uses the recorded count, so a
        # caller passing a different num_partitions can never orphan logs
        # or remap keys.
        self._meta_log = OpLog(self._root / "topics.log")
        self._topic_partitions: dict[str, int] = {}
        for i in range(len(self._meta_log)):
            name, count = _load(self._meta_log.read(i))
            self._topic_partitions[name] = count
        self._offset_path = self._root / "offsets.log"
        self._offset_log = OpLog(self._offset_path)
        for i in range(len(self._offset_log)):
            topic, group, partition, nxt = _load(self._offset_log.read(i))
            self._offsets[(topic, group, partition)] = nxt
        self._offset_records = len(self._offset_log)

    def create_topic(self, name: str, num_partitions: int = 4) -> Topic:
        if name not in self._topics:
            recorded = self._topic_partitions.get(name)
            if recorded is None:
                self._meta_log.append(_dump([name, num_partitions]))
                self._topic_partitions[name] = num_partitions
                recorded = num_partitions
            self._topics[name] = _DurableTopic(name, recorded, self._root)
        return self._topics[name]

    def commit(self, topic: str, group: str, partition: int,
               next_offset: int) -> None:
        if self._offsets.get((topic, group, partition)) == next_offset:
            return
        super().commit(topic, group, partition, next_offset)
        self._offset_log.append(_dump([topic, group, partition, next_offset]))
        self._offset_records += 1
        if self._offset_records > max(self.OFFSET_COMPACT_THRESHOLD,
                                      4 * len(self._offsets)):
            self._compact_offsets()

    def _compact_offsets(self) -> None:
        self._offset_log.close()
        tmp = self._offset_path.with_suffix(".compact")
        tmp.unlink(missing_ok=True)
        fresh = OpLog(tmp)
        for (topic, group, partition), nxt in sorted(self._offsets.items()):
            fresh.append(_dump([topic, group, partition, nxt]))
        fresh.sync()
        fresh.close()
        tmp.replace(self._offset_path)
        self._offset_log = OpLog(self._offset_path)
        self._offset_records = len(self._offset_log)

    def close(self) -> None:
        self._meta_log.close()
        self._offset_log.close()
        for topic in self._topics.values():
            for part in topic.partitions:
                part.close()


# -- durable state store ------------------------------------------------------


class FileStateStore:
    """Journaled key→document store (same duck-typed surface as the
    in-memory StateStore). Every put/append is one journal record; open
    replays the journal into memory. ``compact()`` rewrites the journal as
    one snapshot record per key (the Mongo-compaction analog)."""

    COMPACT_THRESHOLD = 8192

    def __init__(self, root: str | os.PathLike) -> None:
        self._root = Path(root)
        self._root.mkdir(parents=True, exist_ok=True)
        self._path = self._root / "state.log"
        self._journal = OpLog(self._path)
        self._data: dict[str, Any] = {}
        for i in range(len(self._journal)):
            kind, key, value = _load(self._journal.read(i))
            if kind == "put":
                self._data[key] = value
            else:
                self._data.setdefault(key, []).extend(value)
        self._records = len(self._journal)

    def get(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)

    def put(self, key: str, value: Any) -> None:
        data = _dump(["put", key, value])
        self._journal.append(data)
        # Keep the codec-decoded copy so in-memory state is identical to a
        # post-restart replay (tuples become lists etc.) — no dual-shape
        # bugs between first run and recovery. One serialization pass: the
        # journal bytes are the source.
        self._data[key] = _load(data)[2]
        self._bump()

    def append(self, key: str, items: list) -> None:
        data = _dump(["append", key, items])
        self._journal.append(data)
        self._data.setdefault(key, []).extend(_load(data)[2])
        self._bump()

    def _bump(self) -> None:
        self._records += 1
        if self._records > max(self.COMPACT_THRESHOLD, 8 * len(self._data)):
            self.compact()

    def keys(self, prefix: str = "") -> list[str]:
        return sorted(k for k in self._data if k.startswith(prefix))

    def sync(self) -> None:
        self._journal.sync()

    def compact(self) -> None:
        self._journal.close()
        tmp = self._path.with_suffix(".compact")
        tmp.unlink(missing_ok=True)
        fresh = OpLog(tmp)
        for key in self.keys():
            fresh.append(_dump(["put", key, self._data[key]]))
        fresh.sync()
        fresh.close()
        tmp.replace(self._path)
        self._journal = OpLog(self._path)
        self._records = len(self._journal)

    def close(self) -> None:
        self._journal.close()


# -- content-addressed snapshot store -----------------------------------------

CHUNK_BYTES = 64 * 1024


class GitSnapshotStore:
    """gitrest analog: snapshots as sha256-addressed chunked blobs.

    A snapshot serializes to canonical JSON, splits into CHUNK_BYTES
    blobs (each stored once under its content hash — structural sharing
    across summaries for free, like git blobs), and a tree object lists
    the chunk hashes. Heads are per-document ref files. Implements the
    snapshot-backend surface RouterliciousService uses (upload / get /
    head / set_head).
    """

    def __init__(self, root: str | os.PathLike) -> None:
        self._root = Path(root)
        (self._root / "objects").mkdir(parents=True, exist_ok=True)
        (self._root / "refs").mkdir(parents=True, exist_ok=True)

    # -- object plumbing ------------------------------------------------------

    _SHA_RE = re.compile(r"[0-9a-f]{64}")

    def _object_path(self, sha: str) -> Path:
        # Handles arrive from clients (SUMMARIZE op contents) — a malformed
        # one must never touch the filesystem (path traversal).
        if not isinstance(sha, str) or not self._SHA_RE.fullmatch(sha):
            raise KeyError(f"invalid object id {sha!r}")
        return self._root / "objects" / sha[:2] / sha[2:]

    def put_object(self, data: bytes) -> str:
        sha = hashlib.sha256(data).hexdigest()
        path = self._object_path(sha)
        if not path.exists():
            path.parent.mkdir(exist_ok=True)
            tmp = path.with_suffix(".tmp")
            tmp.write_bytes(data)
            tmp.replace(path)  # atomic publish; dedup by content
        return sha

    def get_object(self, sha: str) -> bytes:
        return self._object_path(sha).read_bytes()

    # -- snapshot surface -----------------------------------------------------

    def upload(self, doc_id: str, snapshot: dict, put_object=None) -> str:
        """``put_object`` lets a caching front (historian) write through
        itself so freshly-uploaded chunks are served hot."""
        put = put_object if put_object is not None else self.put_object
        body = json.dumps(to_wire(snapshot), sort_keys=True,
                          separators=(",", ":")).encode()
        chunks = [put(body[i:i + CHUNK_BYTES])
                  for i in range(0, max(len(body), 1), CHUNK_BYTES)]
        tree = json.dumps({"chunks": chunks, "doc": doc_id}).encode()
        return put(tree)

    def get(self, doc_id: str, handle: str | None,
            read_object=None) -> dict | None:
        """``read_object`` lets a caching front substitute its cached
        reader; the tree/chunk format is parsed in exactly one place."""
        read = read_object if read_object is not None else self.get_object
        if handle is None:
            return None
        try:
            tree = json.loads(read(handle).decode())
            body = b"".join(read(c) for c in tree["chunks"])
        except (OSError, ValueError, KeyError, TypeError):
            return None
        return from_wire(json.loads(body.decode()))

    def _ref_path(self, doc_id: str) -> Path:
        safe = hashlib.sha256(doc_id.encode()).hexdigest()[:32]
        return self._root / "refs" / safe

    def head(self, doc_id: str) -> str | None:
        path = self._ref_path(doc_id)
        return path.read_text() if path.exists() else None

    def set_head(self, doc_id: str, handle: str) -> None:
        path = self._ref_path(doc_id)
        tmp = path.with_suffix(".tmp")
        tmp.write_text(handle)
        tmp.replace(path)
