"""Historian — caching proxy in front of the snapshot store.

Reference parity: server/historian (a Redis-backed caching proxy exposing
gitrest's git REST API to drivers and scribe — historian/README.md:1-4).
Here the same role is an in-process read-through cache wrapped around any
snapshot backend with the four-method surface RouterliciousService uses
(upload / get / head / set_head — durable_store.GitSnapshotStore or the
in-memory store). Alfred's snapshot ops and scribe's validation reads go
through it, so repeat reads of hot summaries never touch the backing
object files.

Cache design (instead of the reference's external Redis):
  * content-addressed objects are IMMUTABLE — cached forever under an LRU
    bounded by object count and total bytes;
  * per-document heads are MUTABLE — cached write-through, so a single
    service's reads are coherent; a second historian over the same backend
    sees new heads once its TTL lapses (``head_ttl_s``), mirroring the
    reference's shared-Redis coherence window.
"""

from __future__ import annotations

import time
from collections import OrderedDict

from ..utils import MetricsRegistry


class Historian:
    """Read-through LRU over a snapshot store; same surface + get_object."""

    def __init__(self, backend, max_objects: int = 4096,
                 max_bytes: int = 64 * 1024 * 1024,
                 head_ttl_s: float = 1.0,
                 metrics: MetricsRegistry | None = None,
                 clock=time.monotonic) -> None:
        self._backend = backend
        self._max_objects = max_objects
        self._max_bytes = max_bytes
        self._head_ttl_s = head_ttl_s
        self._clock = clock
        self._metrics = metrics if metrics is not None else MetricsRegistry()
        self._objects: OrderedDict[str, bytes] = OrderedDict()
        self._bytes = 0
        # Bounded like the object cache: long-lived services touch many
        # short-lived documents and must not accumulate heads forever.
        self._max_heads = max(64, max_objects)
        self._heads: OrderedDict[str, tuple[str | None, float]] = \
            OrderedDict()

    # -- object cache ---------------------------------------------------------

    def _remember(self, sha: str, data: bytes) -> None:
        if sha in self._objects:
            self._objects.move_to_end(sha)
            return
        if len(data) > self._max_bytes:
            return  # larger than the whole budget: serve, don't cache
        self._objects[sha] = data
        self._bytes += len(data)
        while (len(self._objects) > self._max_objects
               or self._bytes > self._max_bytes):
            _, evicted = self._objects.popitem(last=False)
            self._bytes -= len(evicted)
            self._metrics.counter("historian.evictions").inc()

    def get_object(self, sha: str) -> bytes:
        cached = self._objects.get(sha)
        if cached is not None:
            self._objects.move_to_end(sha)
            self._metrics.counter("historian.object_hits").inc()
            return cached
        self._metrics.counter("historian.object_misses").inc()
        data = self._backend.get_object(sha)
        self._remember(sha, data)
        return data

    def put_object(self, data: bytes) -> str:
        sha = self._backend.put_object(data)
        self._remember(sha, data)
        return sha

    # -- snapshot surface (what the service binds to) -------------------------

    def upload(self, doc_id: str, snapshot: dict) -> str:
        # Write through OUR put_object when the backend supports injection,
        # so freshly-uploaded chunks serve hot (scribe validates the very
        # summary a client just uploaded).
        if hasattr(self._backend, "put_object"):
            return self._backend.upload(doc_id, snapshot,
                                        put_object=self.put_object)
        return self._backend.upload(doc_id, snapshot)

    def get(self, doc_id: str, handle: str | None) -> dict | None:
        if handle is None:
            return None
        # Reassemble through the object cache when the backend exposes
        # object plumbing (GitSnapshotStore) — the tree/chunk format is
        # parsed only by the backend; otherwise delegate whole.
        if hasattr(self._backend, "get_object"):
            return self._backend.get(doc_id, handle,
                                     read_object=self.get_object)
        return self._backend.get(doc_id, handle)

    def _cache_head(self, doc_id: str, value: str | None,
                    now: float) -> None:
        self._heads[doc_id] = (value, now)
        self._heads.move_to_end(doc_id)
        while len(self._heads) > self._max_heads:
            self._heads.popitem(last=False)

    def head(self, doc_id: str) -> str | None:
        entry = self._heads.get(doc_id)
        now = self._clock()
        if entry is not None and now - entry[1] < self._head_ttl_s:
            self._metrics.counter("historian.head_hits").inc()
            return entry[0]
        value = self._backend.head(doc_id)
        self._cache_head(doc_id, value, now)
        self._metrics.counter("historian.head_misses").inc()
        return value

    def set_head(self, doc_id: str, handle: str) -> None:
        self._backend.set_head(doc_id, handle)
        self._cache_head(doc_id, handle, self._clock())

    def invalidate_heads(self) -> int:
        """Drop every cached head — the failover hook: a leader
        promotion (server/replication.py) rolls journaled head flips
        straight onto the BACKEND, so any historian front still serving
        must not answer from pre-failover entries for up to a TTL.
        Object caches stay — content-addressed chunks are immutable.
        Returns the number of entries dropped."""
        dropped = len(self._heads)
        self._heads.clear()
        return dropped

    def release(self, doc_id: str, handle: str) -> list[str]:
        """GC pass-through (GitSnapshotStore refcounted release), with
        exactly the DELETED objects dropped from the cache — a deleted
        blob must not keep serving from memory as if alive (objects the
        backend kept — shared chunks — stay cached)."""
        release = getattr(self._backend, "release", None)
        if release is None:
            return []
        deleted = release(doc_id, handle)
        for sha in deleted:
            cached = self._objects.pop(sha, None)
            if cached is not None:
                self._bytes -= len(cached)
        return deleted

    # -- observability --------------------------------------------------------

    def stats(self) -> dict:
        snap = self._metrics.snapshot()
        return {
            "objects": len(self._objects),
            "bytes": self._bytes,
            "object_hits": snap.get("historian.object_hits", 0),
            "object_misses": snap.get("historian.object_misses", 0),
            "evictions": snap.get("historian.evictions", 0),
        }
