"""History plane — time-travel reads, named branches, and summarization
compaction over the storm tier's durable record (ROADMAP item 4, the
round-18 tentpole).

Reference parity: the reference's summary contract (``ISummaryTree``,
PAPER.md layer 0) makes rolled-up summaries a first-class protocol
plane — catch-up cost is bounded by the distance to the nearest summary
while the op log keeps intermediate states addressable. Here the same
contract is productized over what the serving tier already journals:
the content-addressed snapshot store (``GitSnapshotStore``/``Historian``
with refcount GC), the per-doc WAL tick index, and the PR 13 cold-read
``records_overlapping``/``get_deltas`` machinery. Three capabilities:

* **time travel** — :meth:`HistoryPlane.read_at` materializes a doc's
  converged map state at ANY historical sequence number entirely from
  the cold path: nearest history summary at-or-below ``seq`` + a scalar
  fold of the WAL records in ``(summary.seq, seq]``. No device row is
  hydrated, no pool slot churns — a read is a read. The scalar fold is
  an EXACT twin of the device LWW kernel (``ops/map_kernel._apply_doc``
  collapsed to sequential per-op application), pinned by the
  materialize-at-N ≡ replay-to-N differential in tests/test_history.py.
* **named branches** — :meth:`fork` seeds a NEW doc from the parent's
  state at ``seq``: the branch's first history summary IS the seeded
  state (so time travel below the fork seq delegates to the parent and
  above it folds the branch's own records), and the serving seed is a
  normal cold-doc record hydrated through the ordinary residency
  recovery path (or installed directly into live rows when no residency
  tier is attached). Branch metadata (parent, fork seq, name) journals
  as a docs-less WAL CONTROL record (the ``"hp"`` header field — the
  mega-doc ``"mg"`` pattern) and rides the storm snapshot, so recovery
  re-seeds a forked branch at the identical point in the total order.
  Forked docs are FULL citizens: residency, migration, QoS and viewers
  see an ordinary doc. :meth:`merge_back` re-submits the branch's delta
  ops (records above the fork seq) through the ordinary sequencer as a
  fresh client's frames — convergence needs no new merge machinery.
* **summarization compaction** — :meth:`maybe_compact` (driven from the
  storm flush maintenance cadence) rolls long WAL tails into fresh
  summaries on op-count/byte thresholds, flips heads atomically through
  the existing ``Historian.set_head``/``release`` refcount GC, and —
  with ``tail_retention_summaries`` set — trims superseded tail
  prefixes: the per-doc tick index drops entries below the floor and
  the superseded WAL tick blobs rewrite to tiny filler records
  (``StormController.trim_tick_blobs``), so a long-tail churn doc's
  disk cost collapses to its summary instead of its whole edit history.
  Reads below the trim floor raise :class:`HistoryError` (the same
  retention trade ``doc_index_retention_ticks`` and scriptorium
  ``retention_ops`` already make); with retention None (the default)
  every intermediate state stays addressable forever.

Safety invariants (chaos-proven, ``history.mid_compaction`` /
``history.mid_fork`` crashpoints, tools/chaos.py ``--history``):

* a kill mid-compaction leaves the previous summary head intact (the
  upload-then-flip order every head in this codebase uses) — the next
  cadence pass re-compacts; nothing acked-durable is touched;
* a kill mid-fork (control journaled, branch not yet seeded) replays
  the control and re-derives the identical seed — the fold is a pure
  function of the records below the control's WAL position;
* compaction + trim never change converged state: the never-compacted
  twin digests byte-identical (state lives in summaries exactly when it
  leaves the tail, and only ticks below the storm checkpoint watermark
  — which recovery never replays — are ever rewritten).

Round-19 additions (ROADMAP 5c/5d): the inline summary chain
re-anchors past ``chain_reanchor_depth`` — the oldest entries roll into
linked content-addressed anchor pages so ``__hist__`` head records stay
O(depth) forever while every anchored exact state remains addressable
through :meth:`_base_for`'s anchor walk — and paid-tier tenants
(riddler weight > 1.0 via ``tenant_source``) can :meth:`pin_range` seq
ranges against the tail-trim and chain-release retention trades; pins
journal as ``"hp"`` controls and ride the storm snapshot like branch
metadata, so they survive recovery and leader failover.
"""

from __future__ import annotations

import dataclasses
import json
import struct
import time
from typing import Any

import numpy as np

from ..ops import map_kernel as mk
from ..utils import faults

#: Format version stamped on every history summary record. Readers
#: accept 0..CURRENT and refuse anything newer.
HISTORY_SUMMARY_VERSION = 1

#: Snapshot-store key prefix for per-doc history summary heads.
HIST_KEY_PREFIX = "__hist__::"


class HistoryError(RuntimeError):
    """A historical read cannot be served: the requested seq is beyond
    the doc's head, or below a compaction trim floor (the retention
    trade — reload from a summary instead)."""


class _FoldState:
    """Scalar twin of one doc's device map row: the EXACT sequential
    equivalent of ``map_kernel._apply_doc`` (set → present/value/vseq,
    delete → absent + vseq, clear → wipe present/vseq, value planes
    untouched — last-writer-wins per slot with clear barriers)."""

    __slots__ = ("present", "value", "vseq", "cleared_seq", "seq")

    def __init__(self, seq: int = 0) -> None:
        self.present: set[int] = set()
        self.value: dict[int, int] = {}
        self.vseq: dict[int, int] = {}
        self.cleared_seq = -1
        self.seq = seq  # the fold frontier this state reflects

    def apply_batch(self, ops: list[tuple[int, int]]) -> None:
        """One TICK's applied ``(word, seq)`` ops for this doc, with the
        device kernel's intra-tick winner rule: ops before the tick's
        last clear are dead (they never touch any plane — a sequential
        fold would leave their values behind on the value plane, which
        the byte-identity bar forbids), and per slot only the LAST
        surviving key-op lands — set writes present/value/vseq, delete
        clears presence and stamps vseq with the value plane untouched.
        For a single op (or a mid-tick prefix) this reduces to the
        sequential rules on every ENTRIES-visible plane."""
        last_clear = -1
        for idx, (word, _seq) in enumerate(ops):
            if (word & 3) == mk.MAP_CLEAR:
                last_clear = idx
        if last_clear >= 0:
            self.present.clear()
            self.vseq.clear()  # device: vseq := -1 everywhere
            self.cleared_seq = ops[last_clear][1]
        winners: dict[int, tuple[int, int]] = {}
        for word, seq in ops[last_clear + 1:]:
            winners[(word >> 2) & 0x3FF] = (word, seq)
        for slot, (word, seq) in winners.items():
            if (word & 3) == mk.MAP_SET:
                self.present.add(slot)
                self.value[slot] = (word >> 12) & 0xFFFFF
                self.vseq[slot] = seq
            else:  # MAP_DELETE
                self.present.discard(slot)
                self.vseq[slot] = seq

    def entries(self) -> dict[str, int]:
        """Converged entries in the canonical ``k<slot>`` key space —
        the same shape ``KernelMergeHost.map_entries`` serves."""
        return {f"k{s}": self.value[s] for s in sorted(self.present)}

    def planes(self, s_live: int
               ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Full-width device planes (present/value/vseq) — the fork
        seed; byte-identical to a row that replayed the same stream."""
        present = np.zeros(s_live, np.bool_)
        value = np.zeros(s_live, np.int32)
        vseq = np.full(s_live, -1, np.int32)
        for slot in self.present:
            present[slot] = True
        for slot, v in self.value.items():
            value[slot] = v
        for slot, sq in self.vseq.items():
            vseq[slot] = sq
        return present, value, vseq

    def to_wire(self) -> dict:
        return {"present": sorted(self.present),
                "value": sorted(self.value.items()),
                "vseq": sorted(self.vseq.items()),
                "cleared_seq": self.cleared_seq, "seq": self.seq}

    @classmethod
    def from_wire(cls, snap: dict) -> "_FoldState":
        st = cls(int(snap["seq"]))
        st.present = {int(s) for s in snap["present"]}
        st.value = {int(s): int(v) for s, v in snap["value"]}
        st.vseq = {int(s): int(v) for s, v in snap["vseq"]}
        st.cleared_seq = int(snap["cleared_seq"])
        return st

    def copy(self) -> "_FoldState":
        st = _FoldState(self.seq)
        st.present = set(self.present)
        st.value = dict(self.value)
        st.vseq = dict(self.vseq)
        st.cleared_seq = self.cleared_seq
        return st


# -- off-leader fold reuse -----------------------------------------------------
#
# The read-replica tier (server/read_replica.py) serves read_at/branch
# reads with NO HistoryPlane instance and NO storm controller — just the
# shared snapshot store and a tailed copy of the WAL. These module-level
# functions are the exact read path the plane itself uses, factored so
# both callers fold the same records through the same code and stay
# byte-identical by construction.


def load_summary_record(snapshots, doc: str) -> dict | None:
    """The doc's head summary record from the shared store (version-
    checked), or None when the doc has never been compacted."""
    handle = snapshots.head(HIST_KEY_PREFIX + doc)
    if handle is None:
        return None
    rec = snapshots.get(HIST_KEY_PREFIX + doc, handle)
    if rec is None:
        return None
    version = rec.get("format_version", 0)
    if not 0 <= version <= HISTORY_SUMMARY_VERSION:
        raise ValueError(
            f"history summary format v{version} is newer than this "
            f"reader (max v{HISTORY_SUMMARY_VERSION})")
    return rec


def summary_base_for(snapshots, doc: str, seq: int,
                     rec: dict | None) -> _FoldState:
    """Nearest exact summary state at-or-below ``seq`` given the doc's
    head summary record ``rec`` (empty state at 0 when nothing covers):
    head state, then the inline chain newest-first, then the linked
    anchor pages of re-anchored older states."""
    if rec is None:
        return _FoldState(0)
    if rec["seq"] <= seq:
        return _FoldState.from_wire(rec["state"])
    key = HIST_KEY_PREFIX + doc
    for s, handle in reversed(rec.get("chain", ())):
        if s <= seq:
            old = snapshots.get(key, handle)
            if old is None:
                return _FoldState(0)  # GC'd: fall to the floor check
            return _FoldState.from_wire(old["state"])
    anchor_handle = (rec.get("anchor") or {}).get("handle")
    while anchor_handle is not None:
        page = snapshots.get(key, anchor_handle)
        if page is None:
            break  # anchor GC'd: fall through to the floor check
        for s, handle in reversed(page.get("entries", ())):
            if s <= seq:
                old = snapshots.get(key, handle)
                if old is None:
                    return _FoldState(0)
                return _FoldState.from_wire(old["state"])
        anchor_handle = page.get("prev_anchor")
    return _FoldState(0)


def fold_storm_records(state: _FoldState, records, to_seq: int,
                       read_tick_words) -> None:
    """Fold storm-shaped doc records in ``(state.seq, to_seq]`` onto
    ``state`` — the scalar twin of the device LWW kernel.
    ``read_tick_words(tick)`` resolves a tick id to its raw op-word
    bytes (leader: the storm's blob log; replica: its tailed WAL)."""
    import base64
    blob_cache: dict[int, bytes] = {}
    for rec in sorted(records, key=lambda r: r["first_seq"]):
        n_seq = rec["n_seq"]
        if n_seq <= 0 or rec["last_seq"] <= state.seq:
            continue
        if "words" in rec:
            words = np.frombuffer(base64.b64decode(rec["words"]),
                                  np.uint32, rec["count"])
        else:
            tick = rec["tick"]
            blob = blob_cache.get(tick)
            if blob is None:
                blob = read_tick_words(tick)
                blob_cache[tick] = blob
            words = np.frombuffer(blob, np.uint32, rec["count"],
                                  rec["w_off"])
        skip = rec["count"] - n_seq  # rejected prefix (dup resend)
        first = rec["first_seq"]
        batch: list[tuple[int, int]] = []
        for j in range(n_seq):
            seq = first + j
            if seq <= state.seq:
                continue
            if seq > to_seq:
                break
            batch.append((int(words[skip + j]), seq))
        if batch:
            # One record = one tick's doc batch: the intra-tick
            # winner rule applies per record.
            state.apply_batch(batch)
        if first + n_seq - 1 > to_seq:
            return


class HistoryPlane:
    """The history subsystem over one :class:`~.storm.StormController`.
    Attaches itself as ``storm.history``; the controller replays its
    ``"hp"`` WAL control records, carries its branch metadata in the
    storm snapshot, and drives :meth:`maybe_compact` from the flush
    maintenance cadence."""

    def __init__(self, storm, snapshots=None,
                 summary_interval_ops: int | None = None,
                 summary_interval_bytes: int | None = None,
                 tail_retention_summaries: int | None = None,
                 max_chain_summaries: int | None = None,
                 chain_reanchor_depth: int | None = 64,
                 tenant_source=None,
                 compact_docs_per_pass: int = 8,
                 compact_check_every: int = 16,
                 trim_batch_ticks: int = 64) -> None:
        self.storm = storm
        self.snapshots = (snapshots if snapshots is not None
                          else storm.snapshots)
        if self.snapshots is None:
            raise ValueError(
                "HistoryPlane needs a snapshot store — summaries and "
                "branch seeds live there (pass snapshots= here or on "
                "the controller)")
        #: None disables the background summarizer (explicit compact()
        #: still works); with a value, maybe_compact() rolls any doc
        #: whose tail is at least this many ops behind its summary.
        self.summary_interval_ops = summary_interval_ops
        self.summary_interval_bytes = summary_interval_bytes
        #: None = never trim (every intermediate state addressable
        #: forever); K = keep the WAL tail for the newest K summary
        #: intervals, trim below (0 = trim everything under the head
        #: summary — maximum disk win, summary-state-only time travel
        #: below it).
        self.tail_retention_summaries = tail_retention_summaries
        #: None = the summary chain keeps EVERY prior summary (each is
        #: tiny — exact states stay addressable forever, the contract
        #: the trim-floor error message promises); K = keep the newest
        #: K chain entries and release older ones through the store's
        #: refcount GC (reads at their seqs then fail like any
        #: compacted-away state).
        self.max_chain_summaries = max_chain_summaries
        #: Inline-chain depth cap (ROADMAP 5c): when the head record's
        #: chain grows past this, compact() rolls the OLDEST entries
        #: into a content-addressed anchor page (a linked list under
        #: the same hist key) and keeps only the newest half inline —
        #: head records stay O(depth) while anchored exact states stay
        #: addressable. None disables re-anchoring (unbounded chain).
        self.chain_reanchor_depth = chain_reanchor_depth
        #: Paid-tier authority for retention pins (ROADMAP 5d): any
        #: object with riddler's ``weight_for(tenant_id)`` surface;
        #: weight > 1.0 (pro/premium) may pin. None = pins ungated
        #: (embedders with their own auth story).
        self.tenant_source = tenant_source
        #: (tenant, doc) -> (lo, hi): seq ranges pinned against the
        #: tail trim and chain release — journaled as "hp" controls
        #: and carried in the storm snapshot like branch metadata.
        self.pins: dict[tuple[str, str], tuple[int, int]] = {}
        self.compact_docs_per_pass = max(1, compact_docs_per_pass)
        self.compact_check_every = max(1, compact_check_every)
        self.trim_batch_ticks = max(1, trim_batch_ticks)
        #: branch doc -> {"parent", "seq", "name"} (journaled as "hp"
        #: controls + the storm snapshot's "history" field).
        self.branches: dict[str, dict] = {}
        self.children: dict[str, list[str]] = {}
        # Summary head cache: doc -> (handle, record). The store stays
        # the authority (heads re-read on miss); compact() refreshes.
        self._summary_cache: dict[str, tuple[str, dict]] = {}
        self._trim_candidates: set[int] = set()
        self._in_replay_control = False
        self._busy = False  # compaction reentrancy (flush-inside-evict)
        self._checks = 0
        m = storm.merge_host.metrics
        self._metrics = m
        self._g_branches = m.gauge("history.branches")
        self._g_branches.set(0)
        self._g_tail = m.gauge("history.tail_ops")
        self._g_tail.set(0)
        self._c_compactions = m.counter("history.compactions")
        self._c_trimmed = m.counter("history.trimmed_ticks")
        self._c_reads = m.counter("history.reads")
        self._c_merges = m.counter("history.merges")
        self._h_read = m.histogram("history.read_s")
        self.stats = {"compactions": 0, "trimmed_ticks": 0, "forks": 0,
                      "merges": 0, "reads": 0, "reanchors": 0,
                      "pins": 0}
        storm.history = self

    # -- store keys ------------------------------------------------------------

    @staticmethod
    def _hist_key(doc_id: str) -> str:
        return HIST_KEY_PREFIX + doc_id

    # -- summary chain ---------------------------------------------------------

    def _summary_record(self, doc: str) -> dict | None:
        cached = self._summary_cache.get(doc)
        handle = self.snapshots.head(self._hist_key(doc))
        if handle is None:
            return None
        if cached is not None and cached[0] == handle:
            return cached[1]
        rec = self.snapshots.get(self._hist_key(doc), handle)
        if rec is None:
            return None
        version = rec.get("format_version", 0)
        if not 0 <= version <= HISTORY_SUMMARY_VERSION:
            raise ValueError(
                f"history summary format v{version} is newer than this "
                f"reader (max v{HISTORY_SUMMARY_VERSION})")
        self._summary_cache[doc] = (handle, rec)
        return rec

    def has_summary(self, doc: str) -> bool:
        return self._summary_record(doc) is not None

    def summary_seq(self, doc: str) -> int:
        rec = self._summary_record(doc)
        return int(rec["seq"]) if rec is not None else 0

    def tail_floor(self, doc: str) -> int:
        """Seqs at-or-below this are served only by exact summary
        states (0 = the full tail is retained)."""
        rec = self._summary_record(doc)
        return int(rec.get("tail_floor", 0)) if rec is not None else 0

    def _base_for(self, doc: str, seq: int) -> _FoldState:
        """Nearest summary state at-or-below ``seq`` (empty state at 0
        when the doc has no covering summary)."""
        return summary_base_for(self.snapshots, doc, seq,
                                self._summary_record(doc))

    # -- tenant retention pins -------------------------------------------------

    def _pin_floor(self, doc: str) -> int | None:
        """Lowest pinned start seq for ``doc`` (None = unpinned)."""
        los = [lo for (_t, d), (lo, _hi) in self.pins.items()
               if d == doc]
        return min(los) if los else None

    def _pinned_at(self, doc: str, seq: int) -> bool:
        return any(d == doc and lo <= seq <= hi
                   for (_t, d), (lo, hi) in self.pins.items())

    def _pin_overlaps(self, doc: str, fs: int, ls: int) -> bool:
        return any(d == doc and lo <= ls and fs <= hi
                   for (_t, d), (lo, hi) in self.pins.items())

    def pin_range(self, tenant_id: str, doc: str, from_seq: int,
                  to_seq: int) -> dict:
        """Pin ``doc``'s seq range ``[from_seq, to_seq]`` against WAL
        tick-blob trimming and summary-chain release on behalf of
        ``tenant_id`` — the paid-tier retention knob (ROADMAP 5d).
        Gated on the riddler tier column when a ``tenant_source`` is
        attached: weight must be > 1.0 (pro/premium); free/standard
        tenants take the plane's default retention trade. One pin per
        (tenant, doc) — re-pinning replaces the range. Journaled as an
        ``"hp"`` control and carried in the storm snapshot, so pins
        survive recovery and failover. Pins protect history from NOW
        on: records a past compaction already trimmed stay trimmed."""
        lo, hi = int(from_seq), int(to_seq)
        if not 0 <= lo <= hi:
            raise ValueError(f"bad pin range [{lo}, {hi}]")
        if self.tenant_source is not None:
            weight = self.tenant_source.weight_for(tenant_id)
            if weight is None or weight <= 1.0:
                raise HistoryError(
                    f"tenant {tenant_id!r} (weight {weight}) cannot "
                    "pin retention: pins are a paid-tier feature "
                    "(riddler weight > 1.0 — pro/premium)")
        now = int(self.storm.service._clock())
        self._append_control({"op": "pin", "tenant": tenant_id,
                              "doc": doc, "lo": lo, "hi": hi}, now)
        self.pins[(tenant_id, doc)] = (lo, hi)
        self.stats["pins"] = len(self.pins)
        return {"tenant": tenant_id, "doc": doc, "lo": lo, "hi": hi}

    def unpin_range(self, tenant_id: str, doc: str) -> bool:
        """Drop the tenant's pin on ``doc`` (journaled); the next
        compaction cadence reclaims what the pin was holding."""
        if (tenant_id, doc) not in self.pins:
            return False
        now = int(self.storm.service._clock())
        self._append_control({"op": "unpin", "tenant": tenant_id,
                              "doc": doc}, now)
        del self.pins[(tenant_id, doc)]
        self.stats["pins"] = len(self.pins)
        return True

    # -- time travel (the read path) -------------------------------------------

    def head_seq(self, doc: str) -> int:
        """The doc's newest addressable seq, cold-path only: the tick
        index frontier (in-RAM or cold-snapshot) or the summary head,
        whichever is newer."""
        storm = self.storm
        last = 0
        ticks = storm._doc_ticks.get(doc)
        if ticks is None and storm.residency is not None \
                and not storm.residency.is_resident(doc):
            ticks = storm.residency.cold_doc_ticks(doc)
        if ticks:
            last = max(ls for _fs, ls, _t in ticks)
        mega = storm.megadoc
        if mega is not None and mega.has_history(doc):
            # A promoted doc's doc-space frontier lives in the combiner
            # mirror (its ticks index under LANE ids, so the scan above
            # stops at the promotion seq). This is what lets fork() and
            # read_at() address a mega-promoted doc directly — the fold
            # below translates lane-era records through the combine
            # logs via records_overlapping (ROADMAP 5b).
            st = mega.docs.get(doc)
            if st is not None and st.mirror is not None:
                last = max(last, int(st.mirror.seq))
        rec = self._summary_record(doc)
        if rec is not None:
            last = max(last, int(rec["seq"]))
        meta = self.branches.get(doc)
        if meta is not None:
            last = max(last, int(meta["seq"]))
        return last

    def read_at(self, doc: str, seq: int) -> dict:
        """Materialize ``doc``'s converged map state at historical
        ``seq`` — entirely from summaries + durable records (no device
        row is touched, cold docs stay cold)."""
        t0 = time.perf_counter()
        seq = int(seq)
        head = self.head_seq(doc)
        if seq > head:
            raise HistoryError(
                f"seq {seq} is beyond the head ({head}) of {doc!r}")
        state = self._state_at(doc, seq)
        self._c_reads.inc()
        self.stats["reads"] += 1
        self._h_read.observe(time.perf_counter() - t0)
        return {"doc": doc, "seq": seq, "head_seq": head,
                "entries": state.entries()}

    def _state_at(self, doc: str, seq: int) -> _FoldState:
        meta = self.branches.get(doc)
        if meta is not None and seq < meta["seq"]:
            # History below the fork lives with the parent.
            return self._state_at(meta["parent"], seq)
        if seq < 0:
            raise HistoryError(f"negative seq {seq}")
        base = self._base_for(doc, seq)
        if base.seq == seq:
            return base
        floor = self.tail_floor(doc)
        if base.seq < floor and seq > base.seq:
            raise HistoryError(
                f"history of {doc!r} below seq {floor} is compacted "
                f"away (tail retention); only the summary chain's "
                f"exact states remain addressable there")
        state = base.copy()
        self._fold_records(doc, state, seq)
        state.seq = seq
        return state

    def _fold_records(self, doc: str, state: _FoldState,
                      to_seq: int) -> None:
        """Fold the doc's durable records in ``(state.seq, to_seq]``
        onto ``state`` — the scalar twin of the device LWW kernel."""
        storm = self.storm
        fold_storm_records(
            state, storm.records_overlapping(doc, state.seq, to_seq),
            to_seq, storm.read_tick_words)

    # -- summarization compaction ----------------------------------------------

    def maybe_compact(self, max_docs: int | None = None) -> list[str]:
        """Background summarizer pass (the storm flush maintenance
        hook): roll any resident doc whose tail is past the op/byte
        thresholds into a fresh summary, bounded docs per pass. No-op
        while thresholds are unset."""
        if self.summary_interval_ops is None \
                and self.summary_interval_bytes is None:
            return []
        self._checks += 1
        if self._checks % self.compact_check_every:
            return []
        if self._busy:
            return []
        budget = max_docs if max_docs is not None \
            else self.compact_docs_per_pass
        compacted: list[str] = []
        worst_tail = 0
        for doc, dt in list(self.storm._doc_ticks.items()):
            if not dt:
                continue
            tail = dt[-1][1] - self.summary_seq(doc)
            worst_tail = max(worst_tail, tail)
            due = (self.summary_interval_ops is not None
                   and tail >= self.summary_interval_ops) or (
                self.summary_interval_bytes is not None
                and tail * 4 >= self.summary_interval_bytes)
            if due and len(compacted) < budget:
                if self.compact(doc) is not None:
                    compacted.append(doc)
        self._g_tail.set(worst_tail)
        return compacted

    def compact(self, doc: str) -> str | None:
        """Roll ``doc``'s WAL tail into a fresh summary: fold records
        above the current summary, upload, flip the head atomically
        (crashpoint between — a kill keeps the previous head), then GC
        superseded chain summaries and trim the tail per the retention
        policy. Returns the new summary handle, or None when there is
        nothing to roll."""
        storm = self.storm
        if self._busy:
            return None
        if storm.wal_degraded:
            # Fsync breaker open: record reads barrier on the group
            # commit, and the trim rewrite needs a durability barrier —
            # neither is coming on a bounded schedule. Skip the cadence
            # pass; the plane compacts once the WAL heals (the
            # residency-eviction refusal pattern).
            return None
        if doc in storm.quarantined:
            return None  # frozen rows; readmit first
        mega = storm.megadoc
        if mega is not None and (mega.is_promoted(doc)
                                 or mega.parent_of(doc)):
            return None  # lane-era records translate on demotion
        self._busy = True
        try:
            rec = self._summary_record(doc)
            base_seq = int(rec["seq"]) if rec is not None else \
                int(self.branches.get(doc, {}).get("seq", 0))
            head_seq = self.head_seq(doc)
            if head_seq <= base_seq:
                return None
            state = self._state_at(doc, head_seq)
            old_handle = self.snapshots.head(self._hist_key(doc))
            chain = [list(e) for e in (rec or {}).get("chain", ())]
            if rec is not None and old_handle is not None:
                chain.append([int(rec["seq"]), old_handle])
            prev_floor = int((rec or {}).get("tail_floor", 0))
            floor = prev_floor
            if self.tail_retention_summaries is not None:
                # Interval boundaries oldest→newest; keep the newest K.
                bounds = [0] + [s for s, _h in chain] + [head_seq]
                cut = max(0, len(bounds) - 1
                          - self.tail_retention_summaries)
                floor = max(prev_floor, bounds[cut])
            # Retention pins: the floor never passes the last chain
            # boundary at-or-below the lowest pinned start, so every
            # pinned seq keeps a reachable fold base above the floor.
            # A pin created after a trim cannot resurrect records
            # (prev_floor wins) — pins protect from now on.
            pin_lo = self._pin_floor(doc)
            if pin_lo is not None and floor > prev_floor:
                bound = max([b for b in [0] + [s for s, _h in chain]
                             if b <= pin_lo], default=0)
                floor = max(prev_floor, min(floor, bound))
            # The chain keeps prior summaries ADDRESSABLE below the
            # floor (exact states; the per-op records between them are
            # what the trim drops). Only the optional chain cap ever
            # releases one — and never a state inside a pinned range.
            released: list = []
            if self.max_chain_summaries is not None \
                    and len(chain) > self.max_chain_summaries:
                cut_n = len(chain) - self.max_chain_summaries
                released, chain = chain[:cut_n], chain[cut_n:]
                if self.pins:
                    keep = [e for e in released
                            if self._pinned_at(doc, int(e[0]))]
                    if keep:
                        released = [e for e in released
                                    if e not in keep]
                        chain = keep + chain
            # Re-anchoring (ROADMAP 5c): past the depth cap, roll the
            # oldest inline entries into a content-addressed anchor
            # page (linked to its predecessor) so the head record
            # stays bounded; _base_for walks the pages for reads below
            # the inline chain.
            anchor = dict((rec or {}).get("anchor") or {}) or None
            if self.chain_reanchor_depth is not None \
                    and len(chain) > self.chain_reanchor_depth:
                keep_n = max(1, self.chain_reanchor_depth // 2)
                rolled, chain = chain[:-keep_n], chain[-keep_n:]
                page = {"kind": "history-anchor",
                        "format_version": HISTORY_SUMMARY_VERSION,
                        "doc": doc,
                        "entries": [list(e) for e in rolled],
                        "prev_anchor": (anchor or {}).get("handle")}
                page_handle = self.snapshots.upload(
                    self._hist_key(doc), page)
                anchor = {"handle": page_handle,
                          "top_seq": int(rolled[-1][0])}
                self.stats["reanchors"] += 1
            new_rec: dict[str, Any] = {
                "kind": "history-summary",
                "format_version": HISTORY_SUMMARY_VERSION,
                "doc": doc, "seq": head_seq, "state": state.to_wire(),
                "chain": chain, "tail_floor": floor,
            }
            if anchor is not None:
                new_rec["anchor"] = anchor
            if doc in self.branches:
                new_rec["branch"] = dict(self.branches[doc])
            key = self._hist_key(doc)
            handle = self.snapshots.upload(key, new_rec)
            # Chaos kill class "mid-compaction": summary uploaded, head
            # NOT yet flipped — the previous summary stays authoritative
            # and the orphan upload is a bounded leak, never a wrong
            # read.
            faults.crashpoint("history.mid_compaction")
            self.snapshots.set_head(key, handle)
            self._summary_cache[doc] = (handle, new_rec)
            # GC chain summaries beyond the cap through the store's
            # refcount release (shared chunks survive).
            release = getattr(self.snapshots, "release", None)
            if release is not None:
                for _s, h in released:
                    try:
                        release(key, h)
                    except Exception:
                        pass  # GC is best-effort
            if floor > prev_floor:
                self._trim_tail(doc, floor)
            self._c_compactions.inc()
            self.stats["compactions"] += 1
            return handle
        finally:
            self._busy = False

    def _trim_tail(self, doc: str, floor: int) -> None:
        """Drop the doc's tick-index entries at-or-below ``floor`` and
        queue the superseded WAL blobs for the filler rewrite. Cold
        docs are skipped (their index rides the cold snapshot — the
        next eviction after a hydrated compaction re-exports)."""
        storm = self.storm
        dt = storm._doc_ticks.get(doc)
        if dt is None:
            return
        removed = [t for _fs, ls, t in dt if ls <= floor]
        storm._doc_ticks[doc] = [e for e in dt if e[1] > floor]
        self._trim_candidates.update(removed)
        if len(self._trim_candidates) >= self.trim_batch_ticks:
            self.trim_now()

    def trim_now(self) -> int:
        """Flush the queued tail trim: rewrite every candidate WAL tick
        that (a) sits below the storm checkpoint watermark (recovery
        never replays it), and (b) is referenced by NO doc's live tick
        index and names only docs whose index is in RAM (a cold doc's
        snapshot-held index must keep its blobs) — to a tiny filler
        record. Indices stay 1:1 with WAL positions; only the bytes
        shrink."""
        storm = self.storm
        if not self._trim_candidates:
            return 0
        cutoff = storm._last_checkpoint_tick
        live: set[int] = set()
        for entries in storm._doc_ticks.values():
            live.update(t for _fs, _ls, t in entries)
        ticks: set[int] = set()
        for t in sorted(self._trim_candidates):
            if t >= cutoff or t in live:
                continue
            try:
                header, _off = storm._parse_header(storm._read_blob(t))
            except Exception:
                continue
            if any(entry[0] not in storm._doc_ticks
                   for entry in header.get("docs", ())):
                continue  # names a doc whose index we cannot see (cold)
            if header.get("mg") is not None \
                    or header.get("hp") is not None:
                continue  # lifecycle controls are never trimmed
            if self.pins and any(
                    self._pin_overlaps(entry[0], int(entry[6]),
                                       int(entry[7]))
                    for entry in header.get("docs", ())):
                continue  # a tenant retention pin covers this tick
            ticks.add(t)
        if not ticks:
            return 0
        from .durable_store import WalDegradedError
        try:
            trimmed = storm.trim_tick_blobs(ticks)
        except WalDegradedError:
            # Breaker opened under us: candidates stay queued; the next
            # healthy cadence pass retries. Never let a sick disk turn
            # maintenance into a serving-thread crash.
            return 0
        self._trim_candidates -= ticks
        self._c_trimmed.inc(trimmed)
        self.stats["trimmed_ticks"] += trimmed
        return trimmed

    # -- named branches --------------------------------------------------------

    def is_branch(self, doc: str) -> bool:
        return doc in self.branches

    def branch_info(self, doc: str) -> dict | None:
        meta = self.branches.get(doc)
        return dict(meta) if meta is not None else None

    def fork(self, doc: str, seq: int, name: str | None = None,
             writer: str | None = None) -> str:
        """Fork ``doc`` at historical ``seq`` into a new branch doc.
        The seed is journaled as a WAL CONTROL record BEFORE it is
        applied (replay re-derives the identical state), the branch's
        first history summary is the seeded state, and the serving seed
        is an ordinary cold-doc record (hydrated through the normal
        residency path) — or a direct live-row install when no
        residency tier is attached. ``writer`` pre-joins one client
        identity in the seed itself (rides the control record, so the
        branch serves deterministically across recoveries without a
        bus-ordered join); ordinary connects work either way. Returns
        the branch doc id."""
        storm = self.storm
        seq = int(seq)
        branch = name if name else f"{doc}@{seq}"
        if branch == doc or branch in self.branches:
            raise ValueError(f"branch id {branch!r} already exists")
        if branch in storm.seq_host._rows:
            raise ValueError(f"doc id {branch!r} is already served")
        residency = storm.residency
        if residency is not None and residency.cold_handle(branch):
            raise ValueError(f"doc id {branch!r} has cold history")
        storm.flush()  # settle: records must cover seq at the head
        head = self.head_seq(doc)
        if not 0 <= seq <= head:
            raise HistoryError(
                f"fork seq {seq} outside [0, {head}] for {doc!r}")
        state = self._state_at(doc, seq)  # raises below a trim floor
        now = int(storm.service._clock())
        event = {"op": "fork", "parent": doc, "seq": seq,
                 "branch": branch, "name": name or branch}
        if writer is not None:
            event["writer"] = writer
        self._append_control(event, now)
        # Durability barrier BEFORE any seed is written: the branch
        # summary and cold record go to the snapshot store durably, and
        # a lost (unfsynced) control would strand them — the cold head
        # would block any re-fork of the name forever. A fork is a
        # control-plane op; one commit latency is the _push_synth_acks
        # precedent. A degraded WAL fails the fork cleanly here, before
        # anything was seeded. (durability="none" keeps no fsync
        # promise anywhere — nothing to barrier on.)
        if storm._group_wal is not None:
            storm._group_wal.sync()
        elif storm._blob_log is not None and storm.durability == "sync":
            storm._blob_log.sync()
        # Chaos kill class "mid-fork": control DURABLE, branch NOT yet
        # seeded — recovery replays the control and re-derives the
        # identical seed from the records below it.
        faults.crashpoint("history.mid_fork")
        self._apply_fork(branch, doc, seq, name or branch, writer, state)
        self.stats["forks"] += 1
        return branch

    def _apply_fork(self, branch: str, parent: str, seq: int,
                    name: str, writer: str | None = None,
                    state: _FoldState | None = None) -> None:
        """Seed one branch (shared by the live path and WAL-control
        replay — both derive the same state, so both converge)."""
        storm = self.storm
        if state is None:
            # Replay path: the branch's own summary head (written by the
            # pre-crash life's apply) is the durable seed — prefer it
            # over re-deriving from the parent, whose tail a LATER
            # compaction may have trimmed past the fork seq by now.
            rec = self._summary_record(branch)
            if rec is not None and int(rec["seq"]) == int(seq):
                state = _FoldState.from_wire(rec["state"])
            else:
                state = self._state_at(parent, seq)
        meta = {"parent": parent, "seq": int(seq), "name": name}
        self.branches[branch] = meta
        self.children.setdefault(parent, []).append(branch)
        # The branch's first history summary IS the seed: reads at the
        # fork seq are exact, reads above fold the branch's own records.
        rec = {"kind": "history-summary",
               "format_version": HISTORY_SUMMARY_VERSION,
               "doc": branch, "seq": int(seq),
               "state": state.to_wire(), "chain": [], "tail_floor": 0,
               "branch": meta}
        key = self._hist_key(branch)
        handle = self.snapshots.upload(key, rec)
        self.snapshots.set_head(key, handle)
        self._summary_cache[branch] = (handle, rec)
        s_live = storm.merge_host._xstate.present.shape[1]
        present, value, vseq = state.planes(s_live)
        cp = self._fresh_checkpoint(seq, writer)
        residency = storm.residency
        if residency is not None:
            # Serving seed = an ordinary cold-doc record: the first
            # connect/frame hydrates it through the NORMAL recovery
            # path — the branch is a full residency citizen from birth.
            from .merge_host import _nd_pack
            from .residency import COLD_DOC_VERSION
            cold: dict[str, Any] = {
                "kind": "cold-doc",
                "format_version": COLD_DOC_VERSION,
                "doc": branch,
                "tick_watermark": storm._tick_counter,
                "sequencer": dataclasses.asdict(cp),
                "map_row": {
                    "present": _nd_pack(present),
                    "value": _nd_pack(value),
                    "vseq": _nd_pack(vseq),
                    "cleared_seq": int(state.cleared_seq),
                    "last_seq": int(seq),
                },
                "doc_ticks": [], "tick_count": 0,
            }
            if residency.host_label is not None:
                cold["home"] = residency.host_label
            ckey = residency._cold_key(branch)
            chandle = self.snapshots.upload(ckey, cold)
            self.snapshots.set_head(ckey, chandle)
            residency.adopt_cold(branch, chandle)
        else:
            # No residency tier: install straight into live rows (the
            # in-process serving shape).
            storm.seq_host.restore(branch, cp)
            mrow = storm._storm_mrow(branch)
            xs = storm.merge_host._xstate
            row = mrow.row
            storm.merge_host._xstate = mk.MapState(
                present=xs.present.at[row].set(present),
                value=xs.value.at[row].set(value),
                vseq=xs.vseq.at[row].set(vseq),
                cleared_seq=xs.cleared_seq.at[row].set(
                    np.int32(state.cleared_seq)))
            mrow.last_seq = int(seq)
        self._g_branches.set(len(self.branches))

    @staticmethod
    def _fresh_checkpoint(seq: int, writer: str | None = None):
        from .sequencer import SequencerCheckpoint
        clients = []
        if writer is not None:
            # Deterministic seeded writer: joined at the fork point with
            # no ops seen (cseq 0) — clock-free (last_update 0) so the
            # seed is identical in every life.
            clients.append({"client_id": writer, "client_seq": 0,
                            "ref_seq": int(seq), "last_update": 0,
                            "can_evict": True, "can_summarize": True,
                            "nack": False})
        return SequencerCheckpoint(
            sequence_number=int(seq), minimum_sequence_number=int(seq),
            last_sent_msn=int(seq), no_active_clients=not clients,
            clients=clients)

    def merge_back(self, branch: str) -> dict:
        """Re-submit the branch's delta ops (records above its fork
        seq) into the PARENT through the ordinary sequencer — a fresh
        client's frames, so convergence is the normal total-order story
        and the merge is journaled/replayed like any other traffic."""
        meta = self.branches.get(branch)
        if meta is None:
            raise KeyError(f"{branch!r} is not a branch")
        storm = self.storm
        storm.flush()
        parent, fork_seq = meta["parent"], meta["seq"]
        floor = self.tail_floor(branch)
        if floor > fork_seq:
            # The branch's own tail compaction trimmed per-op records
            # the merge needs (a summary is a rollup — the individual
            # delta ops are gone). Failing loudly beats silently
            # merging a suffix (the read_at floor contract).
            raise HistoryError(
                f"cannot merge back {branch!r}: its records below seq "
                f"{floor} were compacted away (fork seq {fork_seq}) — "
                "exempt branches from tail trim before merging")
        records = sorted(storm.records_overlapping(branch, fork_seq),
                         key=lambda r: r["first_seq"])
        parts: list[bytes] = []
        blob_cache: dict[int, bytes] = {}
        for rec in records:
            n_seq = rec["n_seq"]
            if n_seq <= 0:
                continue
            tick = rec["tick"]
            blob = blob_cache.get(tick)
            if blob is None:
                blob = storm.read_tick_words(tick)
                blob_cache[tick] = blob
            words = np.frombuffer(blob, np.uint32, rec["count"],
                                  rec["w_off"])
            skip = rec["count"] - n_seq
            parts.append(words[skip:skip + n_seq].tobytes())
        payload = b"".join(parts)
        total = len(payload) // 4
        result = {"branch": branch, "parent": parent,
                  "fork_seq": fork_seq, "merged_ops": total}
        if total == 0:
            return result
        errors: list[dict] = []

        def sink(ack: dict) -> None:
            if isinstance(ack, dict) and ack.get("error"):
                errors.append(ack)

        conn = storm.service.connect(parent, lambda _m: None)
        try:
            storm.service.pump()
            ref = storm.seq_host.checkpoint(parent).sequence_number
            cseq0, off = 1, 0
            chunk = storm.MAX_COUNT
            while off < total:
                n = min(chunk, total - off)
                storm.submit_frame(
                    sink,
                    {"rid": ("merge", branch, cseq0),
                     "docs": [[parent, conn.client_id, cseq0, ref, n]]},
                    memoryview(payload)[off * 4:(off + n) * 4])
                storm.flush()
                cseq0 += n
                off += n
        finally:
            conn.close()
            storm.service.pump()
        if errors:
            raise RuntimeError(
                f"merge_back of {branch!r} shed: {errors[0]}")
        self._c_merges.inc()
        self.stats["merges"] += 1
        result["parent_seq"] = \
            storm.seq_host.checkpoint(parent).sequence_number
        return result

    # -- WAL control records ---------------------------------------------------

    def _append_control(self, event: dict, now: int) -> None:
        """Journal one history lifecycle event as a docs-less tick
        record (the ``"hp"`` header field — the mega-doc ``"mg"``
        pattern): tick ids stay 1:1 with WAL record indices and replay
        re-applies the event at the same point in the total order."""
        if self._in_replay_control:
            return
        storm = self.storm
        storm._harvest()  # every dispatched tick's record lands first
        from .storm import STORM_WAL_VERSION
        header = json.dumps(
            {"v": STORM_WAL_VERSION, "ts": now, "docs": [],
             "hp": event}, separators=(",", ":")).encode()
        blob = struct.pack("<I", len(header)) + header
        tick_id = storm._tick_counter
        storm._tick_counter += 1
        if storm._group_wal is not None:
            idx = storm._group_wal.append([blob])
            assert idx == tick_id, (idx, tick_id)
        elif storm._blob_log is not None:
            idx = storm._blob_log.append(blob)
            assert idx == tick_id, (idx, tick_id)
        else:
            storm._tick_blobs[tick_id] = blob

    def apply_control(self, event: dict, ts: int) -> None:
        """Replay one journaled history event (``_replay_wal``)."""
        self._in_replay_control = True
        try:
            op = event.get("op")
            if op == "fork":
                if event["branch"] not in self.branches:
                    self._apply_fork(event["branch"], event["parent"],
                                     event["seq"], event["name"],
                                     event.get("writer"))
            elif op == "pin":
                self.pins[(event["tenant"], event["doc"])] = (
                    int(event["lo"]), int(event["hi"]))
                self.stats["pins"] = len(self.pins)
            elif op == "unpin":
                self.pins.pop((event["tenant"], event["doc"]), None)
                self.stats["pins"] = len(self.pins)
            elif op in (None, "trimmed"):
                pass  # filler record of a trimmed tick — stateless
            else:
                raise ValueError(f"unknown history control {op!r}")
        finally:
            self._in_replay_control = False

    # -- snapshot state --------------------------------------------------------

    def export_state(self) -> dict:
        """Branch metadata + retention pins for the storm snapshot
        (summaries and seeds are store-resident already — only the
        registries ride here)."""
        return {"branches": {b: dict(m)
                             for b, m in sorted(self.branches.items())},
                "pins": [[t, d, lo, hi] for (t, d), (lo, hi)
                         in sorted(self.pins.items())]}

    def import_state(self, snap: dict) -> None:
        for branch, meta in snap.get("branches", {}).items():
            if branch not in self.branches:
                self.branches[branch] = dict(meta)
                self.children.setdefault(meta["parent"],
                                         []).append(branch)
        for t, d, lo, hi in snap.get("pins", ()):
            self.pins.setdefault((t, d), (int(lo), int(hi)))
        self.stats["pins"] = len(self.pins)
        self._g_branches.set(len(self.branches))


__all__ = ["HistoryPlane", "HistoryError", "HISTORY_SUMMARY_VERSION",
           "HIST_KEY_PREFIX", "load_summary_record", "summary_base_for",
           "fold_storm_records"]
