"""MessageBus over the C++ shuttle — the native ordering transport.

Reference parity: services-ordering-rdkafka — the one place the reference
server runs native code on the op hot path (librdkafka brokering every
raw/sequenced delta). NativeMessageBus implements the exact MessageBus
object model (topics, crc32 key partitioning, consumer-group offsets)
over fluidframework_tpu.native.shuttle's C++ partition logs; values ride
as wire-codec bytes. The pure-Python MessageBus stays the fallback —
``make_message_bus`` picks per toolchain availability.
"""

from __future__ import annotations

from typing import Any

from ..native.shuttle import Shuttle, shuttle_available
from .bus import BusMessage, MessageBus
# _dump/_load are the wire-codec byte serializers the durable bus journals
# with — importing them also registers the RawOperation codec.
from .durable_store import _dump, _load


class NativeTopic:
    def __init__(self, name: str, num_partitions: int) -> None:
        self.name = name
        self._shuttle = Shuttle(num_partitions)

    @property
    def num_partitions(self) -> int:
        return self._shuttle.num_partitions

    def produce(self, key: str, value: Any) -> tuple[int, int]:
        return self._shuttle.produce(key.encode(), _dump(value))

    def read(self, partition: int, from_offset: int,
             max_messages: int | None = None) -> list[BusMessage]:
        records = self._shuttle.read(partition, from_offset, max_messages)
        return [BusMessage(from_offset + i, key.decode(), _load(payload))
                for i, (key, payload) in enumerate(records)]


class NativeMessageBus:
    """Drop-in MessageBus: same surface, C++ partition logs underneath."""

    def __init__(self) -> None:
        self._topics: dict[str, NativeTopic] = {}

    def create_topic(self, name: str, num_partitions: int = 4) -> NativeTopic:
        if name not in self._topics:
            self._topics[name] = NativeTopic(name, num_partitions)
        return self._topics[name]

    def topic(self, name: str) -> NativeTopic:
        return self._topics[name]

    def produce(self, topic: str, key: str, value: Any) -> tuple[int, int]:
        return self._topics[topic].produce(key, value)

    def committed(self, topic: str, group: str, partition: int) -> int:
        return self._topics[topic]._shuttle.committed(group, partition)

    def commit(self, topic: str, group: str, partition: int,
               next_offset: int) -> None:
        self._topics[topic]._shuttle.commit(group, partition, next_offset)

    def close(self) -> None:
        for topic in self._topics.values():
            topic._shuttle.close()


def make_message_bus(prefer_native: bool = True):
    """The native bus when the toolchain allows, else the Python one."""
    if prefer_native and shuttle_available():
        return NativeMessageBus()
    return MessageBus()
