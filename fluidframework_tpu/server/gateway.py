"""Gateway — a web host that loads documents server-side.

Reference parity: server/gateway (the routerlicious web host: login/token
minting, loader bootstrap, server-side container loading for browsers).
Collapsed to its framework-relevant core as an HTTP service in front of an
alfred front door:

  GET /token?doc=<id>          mint a tenant-signed access token
                               (gateway's api token minting; requires a
                               tenant secret — riddler integration)
  GET /doc/<id>                load the container server-side (read-only
                               network driver session) and return its
                               summary JSON — the "server-side render"
  GET /doc/<id>/view           minimal HTML page embedding that state
                               (the loader-bootstrap page analog)
  GET /healthz                 liveness

Run standalone::

    python -m fluidframework_tpu.server.gateway --alfred-port 7070 --port 8080
"""

from __future__ import annotations

import argparse
import html
import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..drivers.tinylicious_driver import TinyliciousDocumentServiceFactory
from ..protocol.messages import ScopeType
from ..runtime.container import Container
from .riddler import sign_token


class Gateway:
    """Loads documents through the network driver on request."""

    def __init__(self, alfred_host: str, alfred_port: int,
                 tenant_id: str | None = None,
                 tenant_secret: str | None = None) -> None:
        self.factory = TinyliciousDocumentServiceFactory(
            host=alfred_host, port=alfred_port)
        self.tenant_id = tenant_id
        self.tenant_secret = tenant_secret

    def mint_token(self, doc_id: str) -> str:
        if self.tenant_secret is None or self.tenant_id is None:
            raise PermissionError("gateway has no tenant secret configured")
        return sign_token(self.tenant_id, self.tenant_secret, doc_id,
                          scopes=[ScopeType.READ, ScopeType.WRITE])

    def render(self, doc_id: str) -> dict:
        """Server-side load: full client stack over the wire, read mode."""
        service = self.factory(doc_id)
        try:
            container = Container.load(service, mode="read")
            return container.summarize()
        finally:
            service.close()


class _Handler(BaseHTTPRequestHandler):
    gateway: Gateway  # set by serve()

    def log_message(self, *args) -> None:  # quiet by default
        pass

    def do_GET(self) -> None:  # noqa: N802 (http.server contract)
        parsed = urlparse(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        try:
            if parsed.path == "/healthz":
                return self._json(200, {"ok": True})
            if parsed.path == "/token":
                doc = parse_qs(parsed.query).get("doc", [None])[0]
                if not doc:
                    return self._json(400, {"error": "missing ?doc="})
                return self._json(200,
                                  {"token": self.gateway.mint_token(doc)})
            is_doc = (len(parts) == 2 and parts[0] == "doc")
            is_view = (len(parts) == 3 and parts[0] == "doc"
                       and parts[2] == "view")
            if is_doc or is_view:
                doc_id = parts[1]
                state = self.gateway.render(doc_id)
                if is_view:
                    body = ("<!doctype html><title>%s</title><h1>%s</h1>"
                            "<pre id=\"fluid-state\">%s</pre>" % (
                                html.escape(doc_id), html.escape(doc_id),
                                html.escape(json.dumps(state, indent=1,
                                                       default=list))))
                    return self._raw(200, body.encode(),
                                     "text/html; charset=utf-8")
                return self._json(200, state)
            return self._json(404, {"error": f"no route {parsed.path!r}"})
        except PermissionError as err:
            return self._json(403, {"error": str(err)})
        except Exception as err:  # surface load failures as 502
            return self._json(502, {"error": repr(err)})

    def _json(self, status: int, payload: dict) -> None:
        self._raw(status, json.dumps(payload, default=list).encode(),
                  "application/json")

    def _raw(self, status: int, body: bytes, ctype: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def serve(gateway: Gateway, host: str = "127.0.0.1", port: int = 0
          ) -> tuple[ThreadingHTTPServer, threading.Thread]:
    """Start the gateway HTTP server on a daemon thread; returns it."""
    handler = type("BoundHandler", (_Handler,), {"gateway": gateway})
    server = ThreadingHTTPServer((host, port), handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server, thread


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--alfred-host", default="127.0.0.1")
    parser.add_argument("--alfred-port", type=int, required=True)
    parser.add_argument("--tenant-id", default=None)
    parser.add_argument("--tenant-secret", default=None)
    args = parser.parse_args(argv)
    gateway = Gateway(args.alfred_host, args.alfred_port,
                      args.tenant_id, args.tenant_secret)
    server, thread = serve(gateway, args.host, args.port)
    print(f"READY {server.server_address[1]}", flush=True)
    thread.join()


if __name__ == "__main__":
    main(sys.argv[1:])
