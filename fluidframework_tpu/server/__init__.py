"""Ordering service: sequencer host, lambda pipeline, op log, local server.

Reference parity: server/routerlicious/packages/* (deli, scriptorium,
broadcaster, scribe, lambdas-driver, memory-orderer, local-server).
"""
