"""Riddler — tenant management, token auth, throttling.

Reference parity: server/routerlicious-base's riddler tenant/auth service
and alfred's JWT validation at the socket front door
(alfred/index.ts:343: ``connect_document`` verifies a tenant-signed JWT
carrying scopes; services-core IThrottler / ITenantManager seams).
Tokens are HS256 JWTs (header.payload.signature, base64url) signed with
the tenant secret — dependency-free via hmac/hashlib.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import secrets
import time
from dataclasses import dataclass, field


class AuthError(Exception):
    pass


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64url(text: str) -> bytes:
    pad = "=" * (-len(text) % 4)
    return base64.urlsafe_b64decode(text + pad)


def sign_token(tenant_id: str, secret: str, document_id: str,
               scopes: list[str], user: str = "",
               lifetime_s: float = 3600.0,
               now: float | None = None) -> str:
    """Mint an HS256 access token (services-client generateToken)."""
    now = time.time() if now is None else now
    header = {"alg": "HS256", "typ": "JWT"}
    claims = {"tenantId": tenant_id, "documentId": document_id,
              "scopes": list(scopes), "user": user,
              "iat": now, "exp": now + lifetime_s}
    signing_input = (_b64url(json.dumps(header, sort_keys=True).encode())
                     + "." +
                     _b64url(json.dumps(claims, sort_keys=True).encode()))
    signature = hmac.new(secret.encode(), signing_input.encode(),
                         hashlib.sha256).digest()
    return signing_input + "." + _b64url(signature)


@dataclass
class Tenant:
    tenant_id: str
    secret: str


class TenantManager:
    """Tenant registry + token validation (riddler's core; tenants persist
    in the given store so a restarted service honors old tokens)."""

    STORE_KEY = "riddler/tenants"

    def __init__(self, store=None) -> None:
        self._store = store
        self._tenants: dict[str, Tenant] = {}
        if store is not None:
            for tenant_id, secret in (store.get(self.STORE_KEY) or {}).items():
                self._tenants[tenant_id] = Tenant(tenant_id, secret)

    def create_tenant(self, tenant_id: str,
                      secret: str | None = None) -> Tenant:
        if tenant_id in self._tenants:
            raise ValueError(f"tenant {tenant_id!r} exists")
        tenant = Tenant(tenant_id, secret or secrets.token_hex(16))
        self._tenants[tenant_id] = tenant
        self._persist()
        return tenant

    def get_tenant(self, tenant_id: str) -> Tenant:
        if tenant_id not in self._tenants:
            raise AuthError(f"unknown tenant {tenant_id!r}")
        return self._tenants[tenant_id]

    def _persist(self) -> None:
        if self._store is not None:
            self._store.put(self.STORE_KEY, {
                t.tenant_id: t.secret for t in self._tenants.values()})

    def validate_token(self, token: str, document_id: str | None = None,
                       now: float | None = None) -> dict:
        """Verify signature, expiry and (optionally) the document binding;
        returns the claims. Raises AuthError on any failure."""
        now = time.time() if now is None else now
        try:
            header_b64, claims_b64, signature_b64 = token.split(".")
            claims = json.loads(_unb64url(claims_b64))
            given = _unb64url(signature_b64)
        except (ValueError, json.JSONDecodeError) as err:
            raise AuthError(f"malformed token: {err}") from err
        tenant = self.get_tenant(claims.get("tenantId", ""))
        expected = hmac.new(tenant.secret.encode(),
                            f"{header_b64}.{claims_b64}".encode(),
                            hashlib.sha256).digest()
        if not hmac.compare_digest(given, expected):
            raise AuthError("bad signature")
        if claims.get("exp", 0) < now:
            raise AuthError("token expired")
        if document_id is not None and claims.get("documentId") != document_id:
            raise AuthError(
                f"token bound to {claims.get('documentId')!r}, "
                f"not {document_id!r}")
        return claims


@dataclass
class _Window:
    start: float
    used: float = 0.0


class Throttler:
    """Fixed-window rate limiter (services-core IThrottler; alfred
    throttles connects and submits per tenant/client). ``try_consume``
    returns None when allowed, else seconds until the window resets."""

    def __init__(self, rate_per_interval: float = 1_000_000,
                 interval_s: float = 1.0,
                 clock=time.monotonic) -> None:
        self.rate = rate_per_interval
        self.interval = interval_s
        self._clock = clock
        self._windows: dict[str, _Window] = {}

    def try_consume(self, key: str, weight: float = 1.0) -> float | None:
        now = self._clock()
        window = self._windows.get(key)
        if window is None or now - window.start >= self.interval:
            window = _Window(start=now)
            self._windows[key] = window
        if window.used + weight > self.rate:
            return max(0.0, window.start + self.interval - now)
        window.used += weight
        return None
