"""Riddler — tenant management, token auth, throttling.

Reference parity: server/routerlicious-base's riddler tenant/auth service
and alfred's JWT validation at the socket front door
(alfred/index.ts:343: ``connect_document`` verifies a tenant-signed JWT
carrying scopes; services-core IThrottler / ITenantManager seams).
Tokens are HS256 JWTs (header.payload.signature, base64url) signed with
the tenant secret — dependency-free via hmac/hashlib.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import secrets
import time
from dataclasses import dataclass, field


class AuthError(Exception):
    pass


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64url(text: str) -> bytes:
    pad = "=" * (-len(text) % 4)
    return base64.urlsafe_b64decode(text + pad)


def sign_token(tenant_id: str, secret: str, document_id: str,
               scopes: list[str], user: str = "",
               lifetime_s: float = 3600.0,
               now: float | None = None) -> str:
    """Mint an HS256 access token (services-client generateToken)."""
    now = time.time() if now is None else now
    header = {"alg": "HS256", "typ": "JWT"}
    claims = {"tenantId": tenant_id, "documentId": document_id,
              "scopes": list(scopes), "user": user,
              "iat": now, "exp": now + lifetime_s}
    signing_input = (_b64url(json.dumps(header, sort_keys=True).encode())
                     + "." +
                     _b64url(json.dumps(claims, sort_keys=True).encode()))
    signature = hmac.new(secret.encode(), signing_input.encode(),
                         hashlib.sha256).digest()
    return signing_input + "." + _b64url(signature)


@dataclass
class Tenant:
    tenant_id: str
    secret: str
    #: Paid-tier column (the QoS weight source): serving fairness weights
    #: derive from the tenant RECORD, not static scheduler config — see
    #: :meth:`TenantManager.weight_for` and server/qos.py weight_source.
    tier: str = "standard"


#: Paid tier -> relative fair-share weight (the deficit scheduler's
#: per-tenant multiplier). Unknown tiers are rejected at create time.
TIER_WEIGHTS = {"free": 0.25, "standard": 1.0, "pro": 2.0,
                "premium": 4.0}


class TenantManager:
    """Tenant registry + token validation (riddler's core; tenants persist
    in the given store so a restarted service honors old tokens)."""

    STORE_KEY = "riddler/tenants"

    def __init__(self, store=None) -> None:
        self._store = store
        self._tenants: dict[str, Tenant] = {}
        if store is not None:
            for tenant_id, rec in (store.get(self.STORE_KEY) or {}).items():
                if isinstance(rec, str):  # legacy store: bare secret
                    self._tenants[tenant_id] = Tenant(tenant_id, rec)
                else:
                    self._tenants[tenant_id] = Tenant(
                        tenant_id, rec["secret"],
                        rec.get("tier", "standard"))

    def create_tenant(self, tenant_id: str,
                      secret: str | None = None,
                      tier: str = "standard") -> Tenant:
        if tenant_id in self._tenants:
            raise ValueError(f"tenant {tenant_id!r} exists")
        if tier not in TIER_WEIGHTS:
            raise ValueError(f"unknown tier {tier!r} "
                             f"(one of {sorted(TIER_WEIGHTS)})")
        tenant = Tenant(tenant_id, secret or secrets.token_hex(16), tier)
        self._tenants[tenant_id] = tenant
        self._persist()
        return tenant

    def get_tenant(self, tenant_id: str) -> Tenant:
        if tenant_id not in self._tenants:
            raise AuthError(f"unknown tenant {tenant_id!r}")
        return self._tenants[tenant_id]

    def set_tier(self, tenant_id: str, tier: str) -> None:
        """Move a tenant between paid tiers (durable; the scheduler
        resolves the new weight on its next compose through
        weight_source and journals it with its state)."""
        if tier not in TIER_WEIGHTS:
            raise ValueError(f"unknown tier {tier!r} "
                             f"(one of {sorted(TIER_WEIGHTS)})")
        self.get_tenant(tenant_id).tier = tier
        self._persist()

    def weight_for(self, tenant_id: str) -> float | None:
        """QoS weight derived from the tenant record's paid tier, or
        None for unknown tenants (the scheduler falls back to its
        default weight — an unauthenticated door must not crash the
        composer)."""
        tenant = self._tenants.get(tenant_id)
        if tenant is None:
            return None
        return TIER_WEIGHTS.get(tenant.tier)

    def tenant_weights(self) -> dict[str, float]:
        """Every registered tenant's derived weight (the static-config
        replacement for ``StormController(tenant_weights=...)``)."""
        return {t.tenant_id: TIER_WEIGHTS[t.tier]
                for t in self._tenants.values()
                if t.tier in TIER_WEIGHTS}

    def _persist(self) -> None:
        if self._store is not None:
            self._store.put(self.STORE_KEY, {
                t.tenant_id: {"secret": t.secret, "tier": t.tier}
                for t in self._tenants.values()})

    def validate_token(self, token: str, document_id: str | None = None,
                       now: float | None = None) -> dict:
        """Verify signature, expiry and (optionally) the document binding;
        returns the claims. Raises AuthError on any failure."""
        now = time.time() if now is None else now
        try:
            header_b64, claims_b64, signature_b64 = token.split(".")
            claims = json.loads(_unb64url(claims_b64))
            given = _unb64url(signature_b64)
        except (ValueError, json.JSONDecodeError) as err:
            raise AuthError(f"malformed token: {err}") from err
        tenant = self.get_tenant(claims.get("tenantId", ""))
        expected = hmac.new(tenant.secret.encode(),
                            f"{header_b64}.{claims_b64}".encode(),
                            hashlib.sha256).digest()
        if not hmac.compare_digest(given, expected):
            raise AuthError("bad signature")
        if claims.get("exp", 0) < now:
            raise AuthError("token expired")
        if document_id is not None and claims.get("documentId") != document_id:
            raise AuthError(
                f"token bound to {claims.get('documentId')!r}, "
                f"not {document_id!r}")
        return claims


@dataclass
class _Window:
    start: float
    used: float = 0.0


class Throttler:
    """Fixed-window rate limiter (services-core IThrottler; alfred
    throttles connects and submits per tenant/client). ``try_consume``
    returns None when allowed, else seconds until the window resets.

    KNOWN DEFECT (pinned by tests/test_riddler.py, fixed by
    :class:`TokenBucket`): a fixed window admits up to 2x the budget
    across a window edge — a full budget in the last instant of window N
    plus another full budget in the first instant of window N+1. Kept as
    the regression reference; new admission points use the token bucket.
    """

    def __init__(self, rate_per_interval: float = 1_000_000,
                 interval_s: float = 1.0,
                 clock=time.monotonic) -> None:
        self.rate = rate_per_interval
        self.interval = interval_s
        self._clock = clock
        self._windows: dict[str, _Window] = {}

    def try_consume(self, key: str, weight: float = 1.0) -> float | None:
        now = self._clock()
        window = self._windows.get(key)
        if window is None or now - window.start >= self.interval:
            window = _Window(start=now)
            self._windows[key] = window
        if window.used + weight > self.rate:
            return max(0.0, window.start + self.interval - now)
        window.used += weight
        return None


class TokenBucket:
    """Per-key token-bucket rate limiter — the admission primitive.

    Each key accrues ``rate_per_s`` tokens/second up to ``burst``;
    ``try_consume`` spends ``weight`` tokens and returns None, or returns
    the seconds until enough tokens accrue (the ``retry_after_s`` hint).
    Unlike the fixed window it is burst-safe at any boundary: over ANY
    interval T the admitted weight is bounded by ``burst + rate*T`` —
    there is no window edge where 2x the budget slips through.
    Same ``try_consume`` surface as :class:`Throttler`, so the front
    doors take either.
    """

    def __init__(self, rate_per_s: float, burst: float | None = None,
                 clock=time.monotonic) -> None:
        if rate_per_s <= 0:
            raise ValueError(f"rate_per_s must be > 0, got {rate_per_s}")
        self.rate = float(rate_per_s)
        self.burst = float(burst) if burst is not None \
            else max(1.0, self.rate)
        self._clock = clock
        self._buckets: dict[str, list[float]] = {}  # key -> [tokens, at]

    #: Sweep trigger: above this many tracked keys, inserting a new one
    #: first evicts every bucket that has refilled to FULL (a full
    #: bucket is indistinguishable from an absent one) — per-client keys
    #: churn (one per driver instance), and the admission layer must not
    #: itself grow without bound.
    MAX_IDLE_BUCKETS = 4096

    def _bucket(self, key: str, now: float) -> list[float]:
        bucket = self._buckets.get(key)
        if bucket is None:
            if len(self._buckets) > self.MAX_IDLE_BUCKETS:
                for stale in [k for k, b in self._buckets.items()
                              if b[0] + (now - b[1]) * self.rate
                              >= self.burst]:
                    del self._buckets[stale]
            bucket = [self.burst, now]
            self._buckets[key] = bucket
        return bucket

    def try_consume(self, key: str, weight: float = 1.0) -> float | None:
        now = self._clock()
        bucket = self._bucket(key, now)
        tokens = min(self.burst,
                     bucket[0] + (now - bucket[1]) * self.rate)
        bucket[1] = now
        if tokens >= weight:
            bucket[0] = tokens - weight
            return None
        if weight > self.burst and tokens >= self.burst - 1e-9:
            # Oversized request (weight can never fit the burst): admit
            # at a FULL bucket and carry the deficit as debt — the debt
            # refills before anything else admits, so long-run rate
            # holds, and the caller is never livelocked by a hint it can
            # never satisfy.
            bucket[0] = tokens - weight
            return None
        bucket[0] = tokens
        # Hint = time until admittable: a full bucket for oversized
        # requests, `weight` tokens otherwise.
        return (min(weight, self.burst) - tokens) / self.rate

    def refund(self, key: str, weight: float = 1.0) -> None:
        """Return tokens spent on an admission a LATER tier refused —
        one client exhausting its own bucket must not drain the shared
        tenant bucket for its neighbours."""
        bucket = self._buckets.get(key)
        if bucket is not None:
            bucket[0] = min(self.burst, bucket[0] + weight)

    #: Reservation ceiling: refusals reserve at most this many seconds of
    #: future capacity; beyond it the herd's own backoff takes over.
    RESERVE_HORIZON_S = 60.0

    def reserve(self, key: str, weight: float = 1.0
                ) -> tuple[float | None, bool]:
        """``try_consume`` whose refusal RESERVES a future admission slot
        (tokens go negative); returns ``(retry_hint, slot_reserved)``. A
        synchronized herd refused in one instant gets hints that ladder
        at the bucket's own drain rate — the N-th refusal waits ~N/rate —
        so honoring ``retry_after_s`` re-spreads the herd instead of
        re-colliding it one hint later (the thundering-herd property the
        reconnect-storm scenario asserts). The reservation tail is capped
        at ``RESERVE_HORIZON_S``; past it the hint stops growing, NOTHING
        is debited, and ``slot_reserved`` is False — callers must not
        treat such a refusal as claimable (an unbacked claim would admit
        for free later); client-side backoff carries the spread."""
        now = self._clock()
        bucket = self._bucket(key, now)
        tokens = min(self.burst,
                     bucket[0] + (now - bucket[1]) * self.rate)
        bucket[1] = now
        if tokens >= weight:
            bucket[0] = tokens - weight
            return None, False
        if tokens > -self.rate * self.RESERVE_HORIZON_S:
            bucket[0] = tokens - weight  # reserve the future slot
            return (weight - tokens) / self.rate, True
        return (weight - tokens) / self.rate, False  # horizon full


class AdmissionController:
    """Token-bucket admission control for the front doors and the
    batched tick ingress (the alfred/deli throttling seam of the
    reference, rebuilt burst-safe).

    Two tiers per op class — a per-tenant bucket shared by all of a
    tenant's clients and a per-client bucket — consumed in that order
    (with a tenant refund when only the client tier refuses). A refusal
    returns the ``retry_after_s`` hint the busy-nack carries.

    Shedding is DETERMINISTIC under queue pressure: hosts register
    pressure probes (0.0 = idle, 1.0 = inbound queue full); signals shed
    first (``SHED_SIGNALS_AT``), reads next (``SHED_READS_AT``), writes
    only when the queue is genuinely full or their own buckets refuse —
    signals/reads before writes, always in that order, so overload
    degrades the same way every time instead of by arrival race.
    """

    SHED_SIGNALS_AT = 0.50
    SHED_READS_AT = 0.75
    SHED_WRITES_AT = 1.00

    def __init__(self,
                 connect_rate_per_s: float = 100.0,
                 connect_burst: float | None = None,
                 write_rate_per_s: float = 100_000.0,
                 write_burst: float | None = None,
                 client_write_rate_per_s: float | None = None,
                 client_write_burst: float | None = None,
                 pressure_retry_s: float = 0.05,
                 clock=time.monotonic) -> None:
        self.connects = TokenBucket(connect_rate_per_s, connect_burst,
                                    clock=clock)
        self.writes = TokenBucket(write_rate_per_s, write_burst,
                                  clock=clock)
        # Per-client fairness tier: one hot client must not starve its
        # tenant's neighbours. Default = a quarter of the tenant budget.
        self.client_writes = TokenBucket(
            client_write_rate_per_s if client_write_rate_per_s is not None
            else max(1.0, write_rate_per_s / 4),
            client_write_burst, clock=clock)
        self.pressure_retry_s = pressure_retry_s
        self._clock = clock
        # Claimable connect reservations: (tenant, client) -> admission
        # time. A refused connect debits the tenant bucket ONCE
        # (TokenBucket.reserve) and the client claims that slot on
        # return — no re-debit, so the herd drains at exactly the
        # bucket rate instead of compounding its own debt.
        self._connect_reservations: dict[tuple[str, str], float] = {}
        self._probes: list = []
        self.stats = {"admitted_writes": 0, "shed_writes": 0,
                      "shed_reads": 0, "shed_signals": 0,
                      "shed_connects": 0}

    # -- queue-pressure probes -------------------------------------------------

    def add_pressure_probe(self, probe) -> None:
        """Register a 0..1 inbound-queue-fill callable (the storm
        controller's pending-doc ratio, a session's outbox depth, ...)."""
        self._probes.append(probe)

    def pressure(self) -> float:
        return max((float(p()) for p in self._probes), default=0.0)

    def _pressure_retry(self, pressure: float) -> float:
        # Deeper queues hint longer retries so retry waves spread out.
        return self.pressure_retry_s * max(1.0, 4.0 * pressure)

    # -- op classes ------------------------------------------------------------

    def admit_connect(self, tenant_id: str, client_key: str | None = None
                      ) -> float | None:
        """Connect admission (alfred throttles connects per tenant).
        Connects are control-plane: they shed on their bucket only, never
        on data-queue pressure (a full tick queue must not lock clients
        out of reattaching in read mode). Refusals RESERVE a future slot
        (TokenBucket.reserve, debited once) which the client CLAIMS by
        returning at/after its hint — so a reconnect storm's retries
        ladder out at exactly the drain rate instead of re-colliding and
        compounding debt."""
        if client_key is not None:
            rkey = (tenant_id, client_key)
            reserved_at = self._connect_reservations.get(rkey)
            if reserved_at is not None:
                wait = reserved_at - self._clock()
                if wait <= 1e-9:
                    del self._connect_reservations[rkey]
                    return None  # claiming the already-debited slot
                self.stats["shed_connects"] += 1
                return wait  # came back early; same slot stands
            if len(self._connect_reservations) > 4096:
                # Clients that never came back leave unclaimed entries;
                # sweep the long-expired ones so the controller built to
                # bound memory does not itself grow without bound.
                horizon = self._clock() - TokenBucket.RESERVE_HORIZON_S
                for key in [k for k, at in
                            self._connect_reservations.items()
                            if at < horizon]:
                    del self._connect_reservations[key]
        if client_key is None:
            # Keyless (legacy) clients cannot claim a reservation, so a
            # refusal must not RESERVE — each retry would re-debit the
            # shared tenant bucket into unclaimable compounding debt,
            # locking the whole tenant out.
            retry = self.connects.try_consume(f"tenant/{tenant_id}")
            if retry is not None:
                self.stats["shed_connects"] += 1
            return retry
        retry, reserved = self.connects.reserve(f"tenant/{tenant_id}")
        if retry is not None:
            # Tenant-tier refusal: record a claimable slot ONLY when
            # reserve() actually DEBITED one (a reservation without a
            # debit — horizon-full refusals included — would admit for
            # free at claim time, bypassing both buckets).
            if reserved:
                self._connect_reservations[rkey] = self._clock() + retry
            self.stats["shed_connects"] += 1
            return retry
        retry = self.connects.try_consume(f"client/{client_key}")
        if retry is not None:
            # Client-tier refusal: refund the tenant, record NOTHING
            # (nothing stayed debited); the client retries through
            # the normal path on its own backoff.
            self.connects.refund(f"tenant/{tenant_id}")
            self.stats["shed_connects"] += 1
        return retry

    def admit_write(self, tenant_id: str, client_id: str | None = None,
                    weight: float = 1.0) -> float | None:
        pressure = self.pressure()
        if pressure >= self.SHED_WRITES_AT:
            self.stats["shed_writes"] += 1
            return self._pressure_retry(pressure)
        retry = self.writes.try_consume(f"tenant/{tenant_id}", weight)
        if retry is None and client_id is not None:
            retry = self.client_writes.try_consume(
                f"client/{client_id}", weight)
            if retry is not None:
                self.writes.refund(f"tenant/{tenant_id}", weight)
        if retry is not None:
            self.stats["shed_writes"] += 1
            return retry
        self.stats["admitted_writes"] += 1
        return None

    def admit_read(self, tenant_id: str) -> float | None:
        pressure = self.pressure()
        if pressure >= self.SHED_READS_AT:
            self.stats["shed_reads"] += 1
            return self._pressure_retry(pressure)
        return None

    def admit_signal(self, tenant_id: str) -> float | None:
        pressure = self.pressure()
        if pressure >= self.SHED_SIGNALS_AT:
            self.stats["shed_signals"] += 1
            return self._pressure_retry(pressure)
        return None
