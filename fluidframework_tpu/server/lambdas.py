"""Lambda hosting framework — partition pumps + per-document demux.

Reference parity: server/routerlicious/packages/lambdas-driver —
``KafkaRunner`` → ``PartitionManager`` (one pump per partition,
partitionManager.ts:24) → ``DocumentLambda`` router (document-router/*)
demuxing each partition's stream into per-document lambda instances, with
offset checkpointing after each processed batch (restart-safe:
kafka-service/checkpointManager.ts:24). The ``IPartitionLambdaFactory``
seam here is where the batched TPU deli kernel plugs in (BASELINE.json).
"""

from __future__ import annotations

from typing import Protocol

from .bus import BusMessage, Consumer, MessageBus


class DocumentLambda(Protocol):
    """Per-document stream processor (IPartitionLambda, per-doc demuxed)."""

    def handler(self, message: BusMessage) -> None:
        """Process one message (value carries the doc-scoped payload)."""
        ...

    def checkpoint(self, next_offset: int) -> None:
        """Persist state keyed to the partition offset (crash replay lands
        at-or-before this point; handler must dedup)."""
        ...


class DocumentLambdaFactory(Protocol):
    def create(self, doc_id: str) -> DocumentLambda:
        ...


class PartitionManager:
    """Pumps every partition of one topic through per-document lambdas.

    Restart safety: committed offsets + per-doc lambda checkpoints are
    durable; a new PartitionManager over the same bus/store resumes where
    the last one crashed, re-delivering only uncommitted messages.
    """

    def __init__(self, bus: MessageBus, topic: str, group: str,
                 factory: DocumentLambdaFactory,
                 batch_size: int = 256) -> None:
        self._consumer = Consumer(bus, topic, group)
        self._factory = factory
        self._batch_size = batch_size
        self._docs: dict[str, DocumentLambda] = {}

    def _lambda_for(self, doc_id: str) -> DocumentLambda:
        if doc_id not in self._docs:
            self._docs[doc_id] = self._factory.create(doc_id)
        return self._docs[doc_id]

    def pump(self) -> int:
        """Drain every partition; returns messages processed.

        Each round polls ONE batch from EVERY partition and runs all
        handlers before any checkpoint, so a lambda factory that batches
        across documents (the device deli) sees one global tick per round
        instead of one per partition. Documents are partition-sticky, so
        per-document ordering is unaffected by the interleaving.
        """
        processed = 0
        while True:
            round_batches = []
            for partition in range(self._consumer.num_partitions):
                batch = self._consumer.poll(partition, self._batch_size)
                if batch:
                    round_batches.append((partition, batch))
            if not round_batches:
                return processed
            touched: dict[str, int] = {}
            for _, batch in round_batches:
                next_offset = batch[-1].offset + 1
                for message in batch:
                    self._lambda_for(message.key).handler(message)
                    touched[message.key] = next_offset
                processed += len(batch)
            # Checkpoint order matters: lambda state FIRST, offset commit
            # SECOND — a crash between them replays messages the state
            # already saw (dedup guards), never skips unseen ones.
            for doc_id, next_offset in touched.items():
                self._docs[doc_id].checkpoint(next_offset)
            for partition, batch in round_batches:
                self._consumer.commit(partition, batch[-1].offset + 1)
