"""Columnar op-storm fast path — the batched-cadence deployment of the
deli → scriptorium/broadcaster → merger pipeline in ONE fused device tick.

Reference parity: the reference reaches throughput by batching at every
hop — socket.io message arrays, Kafka produce batches
(services-ordering-rdkafka), Mongo batch inserts (scriptorium
lambda.ts:95) — while each document's ticket loop stays per-op JavaScript
(deli/lambda.ts:236). Here the batching goes all the way through the
sequencer: a storm frame carries a whole op batch as packed u32 words
(4 bytes/op, protocol/codec.py storm framing); the host never touches a
per-op Python object between the socket and the device. One flush =

  1. deli      — the CLOSED-FORM storm ticket sequences every doc's
                 batch (full NACK/MSN/dup/gap semantics collapsed to
                 O(1)-per-doc algebra, ops/sequencer.py storm_tickets),
  2. merger    — the Pallas VMEM map fold applies the sequenced ops
                 using the ticket windows WITHOUT a host round-trip
                 (fused jit, ops/map_pallas.py),
  3. scriptorium — one durable columnar record per (doc, tick)
                 (the Mongo batch-insert analog; per-op messages are
                 materialized lazily on the read path, see
                 :func:`materialize_storm_records`),
  4. broadcaster — one compact frame per doc into the fan-out hop,
  5. alfred    — per-frame acks pushed back to the submitting session.

Delivery contract: at-least-once with kernel-side dedup — an un-acked
frame may be resent verbatim; ops whose client_seq the sequencer has
already seen come back OUT_IGNORED (exactly the reference's
clientSequenceNumber dedup, deli/lambda.ts:257).

Storm channels hold LITERAL small-int values (the 20-bit word payload)
addressed by key slot (``k{slot}``); they are the op-storm/load-test
shape (LoadTestDataStore counters), not a general SharedMap replacement —
mixed dict-path traffic on a storm channel is rejected.
"""

from __future__ import annotations

import base64
import functools
import time
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import map_kernel as mk
from ..ops import map_pallas as mp
from ..ops import matrix_kernel as mxk
from ..ops import mergetree_blocks as mtb
from ..ops import mergetree_kernel as mtk
from ..ops import opcodes as oc
from ..ops import sequencer as seqk
from ..ops import tree_kernel as tk
from ..protocol.codec import TRACE_KEY, trace_context
from ..protocol.messages import MessageType, SequencedDocumentMessage
from ..utils import compile_cache, faults
from .kernel_host import KernelSequencerHost, _next_pow2
from .merge_host import ChannelKey, KernelMergeHost

I32 = jnp.int32


_libc = None


def _malloc_trim() -> None:
    """Release retained glibc arena pages back to the OS (no-op where
    unavailable)."""
    global _libc
    if _libc is None:
        import ctypes

        try:
            _libc = ctypes.CDLL("libc.so.6")
        except OSError:
            _libc = False
    if _libc:
        try:
            _libc.malloc_trim(0)
        except Exception:
            pass


class _TrimGate:
    """Rate limiter for the RSS-hygiene ``malloc_trim`` — the round-5
    serving-loop stall suspect (COVERAGE "Round 6 — known regressions"):
    the call walks every glibc arena and can stall the loop under
    allocation churn. It now runs at most once per :meth:`due` poll
    (callers poll once per flush, OFF the per-tick harvest path) and only
    when BOTH gates open: every ``every`` ticks AND at least ``floor_s``
    of wall clock since the last trim."""

    def __init__(self, every: int = 32, floor_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.every = max(1, every)
        self.floor_s = floor_s
        self._clock = clock
        self._last_trim = clock()
        self._trimmed_at_tick = 0

    def due(self, ticks: int) -> bool:
        if ticks - self._trimmed_at_tick < self.every:
            return False
        now = self._clock()
        if now - self._last_trim < self.floor_s:
            return False
        self._last_trim = now
        self._trimmed_at_tick = ticks
        return True


class _Frame(NamedTuple):
    push: Callable[[dict], None] | None
    rid: Any
    docs: list[tuple[str, str, int, int, int]]  # (doc, client, cseq0, ref, n)
    words: np.ndarray   # u32[sum(counts)] VIEW aliasing the receive buffer
    counts: np.ndarray  # i32[n_docs] per-doc op counts
    meta: np.ndarray    # i32[n_docs, 3] (cseq0, ref, count) columns
    trace: Any = None   # (client tc, session scope) tracer key or None
    staged_ns: tuple = (0, 0)  # (decode, admit) ns refunded on shed
    mega: Any = None    # per-entry mega-doc descriptors (megadoc.py)
    tenant: str = "default"  # session-validated tenant (QoS composition)
    t0: int = 0         # ingress monotonic ns (per-tenant ack latency)


def _map_leg(map_state: mk.MapState, words, lo, hi, seq0_for):
    """Windowed map LWW fold: the merger leg of the fused tick. ``lo``/
    ``hi`` bound each row's sequenced op window within ``words``;
    ``seq0_for`` is the row's doc seq before the first windowed op."""
    k = words.shape[1]
    if mp.default_interpret():
        iota = jnp.arange(k, dtype=I32)[None, :]
        words_u = words.astype(jnp.uint32)
        sequenced = (iota >= lo[:, None]) & (iota < hi[:, None])
        map_ops = mk.MapOpBatch(
            valid=sequenced,
            kind=(words_u & 3).astype(I32),
            slot=((words_u >> 2) & 0x3FF).astype(I32),
            value=((words_u >> 12) & 0xFFFFF).astype(I32),
            seq=seq0_for[:, None] + 1 + iota - lo[:, None],
        )
        return jax.vmap(mk._apply_doc)(map_state, map_ops)
    # VMEM LWW fold (ops/map_pallas.py): HBM traffic = planes +
    # 4 bytes/op; the [B, K, S] dense-winner intermediates of the
    # XLA path were the fused tick's dominant cost.
    return mp.fold_words(map_state, words, lo, hi, seq0_for)


def _ticket_window(counts, k: int, dups, n_seq_doc, seq_before):
    """Per-op (in_window, seq) planes from the closed-form ticket: ops
    [dups, dups+n_seq) of each row's batch sequence as seq_before+1…"""
    lo = dups
    hi = jnp.minimum(dups + n_seq_doc, counts)
    iota = jnp.arange(k, dtype=I32)[None, :]
    in_win = (iota >= lo[:, None]) & (iota < hi[:, None])
    seq = seq_before[:, None] + 1 + iota - lo[:, None]
    return in_win, seq


# Device kernel-stats plane: one tiny i32[KSTATS_WIDTH] vector riding
# the tick's EXISTING readback batch (zero extra device syncs). Indices
# are shared by _storm_tick and _mixed_tick so the harvest/export path
# is layout-agnostic; legs a tick does not run report 0 (the map-only
# _storm_tick never rebalances, so its rebalance cells stay 0 — the
# counters move on the mixed/text serving path and in the merge-host
# pre_tick metrics).
KSTAT_SEQUENCED = 0
KSTAT_DUP_OPS = 1
KSTAT_SENTINEL_DOCS = 2
KSTAT_REBALANCE_FIRED = 3   # ticks whose block-table rebalance fired
KSTAT_BLOCKS_TOUCHED = 4    # blocks the spill/rebuild moved this tick
KSTATS_WIDTH = 5


# Packed-plane field orders for the mixed tick's one-array-per-family
# feed (index 0 is always the submission-valid plane; ``seq`` planes are
# OMITTED — the on-device ticket assigns them).
TEXT_PACK = ("valid", "kind", "pos", "end", "ref_seq", "client",
             "pool_start", "text_len", "prop_key", "prop_val")
MATRIX_PACK = ("valid", "target", "kind", "pos", "end", "count",
               "handle_base", "row", "col", "value", "ref_seq", "client")
TREE_PACK = ("valid", "kind", "node", "parent", "trait", "payload")
#: Columns of the [B, 6] per-doc scalar pack.
SCALAR_PACK = ("slot", "cseq0", "ref", "ts", "seq_counts", "map_counts")


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))
def _mixed_tick(seq_state: seqk.SequencerState,
                map_state, merge_state, matrix_state, tree_state,
                scalars, map_words, text_pack, matrix_pack, tree_pack):
    """ALL-FAMILY fused tick: one closed-form deli ticket sequences every
    document's batch, then EACH channel family applies its rows' windowed
    ops in the same device program — map (LWW fold), merge-tree (segment
    table scan), matrix (two-axis vectors + cells) and tree — exactly the
    reference's one-deltas-stream-for-all-op-types contract
    (deli/lambda.ts:82, scriptorium/lambda.ts:16) with the family routing
    done by per-family valid planes instead of message inspection.

    Family rows share the document axis (row i of every family state IS
    document i); a family whose valid-plane row is empty no-ops on that
    document. Families not configured pass ``None`` and trace away. Per
    family, ALL op planes arrive as ONE packed i32[B, F, K] array (field
    order ``*_PACK``) and the per-doc sequencer inputs as one i32[B, 6]
    (``SCALAR_PACK``) — a tick is five host→device transfers total, not
    one per plane (each transfer pays a dispatch on a tunneled
    attachment). Ops carry NO seq planes — the ticket assigns seqs on
    device, so sequencing and application never split across a host
    round trip.
    """
    slot, cseq0, ref, ts, seq_counts, map_counts = (
        scalars[:, i] for i in range(6))
    seq_before = seq_state.seq
    seq_state, dups, n_seq_doc, msn_doc = seqk.storm_tickets(
        seq_state, slot, cseq0, ref, ts, seq_counts)

    if map_words is not None:
        lo = dups
        hi = jnp.minimum(dups + n_seq_doc, map_counts)
        map_state = _map_leg(map_state, map_words, lo, hi, seq_before)

    def unpack(pack, names):
        fields = {name: pack[:, i] for i, name in enumerate(names)}
        valid = fields.pop("valid") != 0
        counts = jnp.sum(valid.astype(I32), axis=1)
        win, seqs = _ticket_window(counts, pack.shape[2], dups,
                                   n_seq_doc, seq_before)
        return fields, valid & win, seqs

    text_overflow = None
    rebalance_stats = jnp.zeros((2,), I32)
    if text_pack is not None:
        fields, valid, seqs = unpack(text_pack, TEXT_PACK)
        ops = mtk.MergeOpBatch(valid=valid, seq=seqs, **fields)
        # THE text serving path: the block-structured table
        # (ops/mergetree_blocks.py, O(S/Bk + Bk) per op) replaces the
        # flat O(S)-per-op scan that dominated the mixed tick (VERDICT
        # r5 weak #4), with the block zamboni FUSED into the same
        # program: when any block runs low on worst-case headroom the
        # state spills ONLY the overfull blocks into their neighbors
        # (incremental re-layout; the full pack + uniform redistribution
        # is the fallback, and the tombstone drop at each doc's new MSN
        # is DEFERRED behind the blk_tomb pressure threshold) — the
        # choose_block_geometry contract that makes serving overflow
        # unreachable, at a per-fire cost of log2(Bk) local shifts
        # instead of two log2(S) cascades. rebalance_stats ([fired,
        # blocks_touched]) rides the kstats readback so the decision
        # rate is attributable without extra syncs.
        merge_state, text_overflow = mtb._apply_tick_impl(merge_state,
                                                          ops)
        merge_state, rebalance_stats = mtb._maybe_rebalance_impl(
            merge_state, msn_doc, text_pack.shape[2])
    if matrix_pack is not None:
        fields, valid, seqs = unpack(matrix_pack, MATRIX_PACK)
        ops = mxk.MatrixOpBatch(valid=valid, seq=seqs, **fields)
        matrix_state = jax.vmap(mxk._process_doc)(matrix_state, ops)
    tree_overflow = None
    if tree_pack is not None:
        fields, valid, _seqs = unpack(tree_pack, TREE_PACK)
        ops = tk.TreeOpBatch(valid=valid, **fields)
        tree_state, tree_out = tk.apply_tick(tree_state, ops)
        tree_overflow = jnp.sum(tree_out.overflow.astype(I32), axis=1)

    n_seq = n_seq_doc
    first = jnp.where(n_seq > 0, seq_before + 1, oc.INT32_MAX)
    last = jnp.where(n_seq > 0, seq_before + n_seq, 0)
    # The mixed tick's kstats vector (same indices as _storm_tick's):
    # sequenced / dup-dropped totals over rows that submitted a batch,
    # no sentinel leg here, and the text rebalance counters.
    live = seq_counts > 0
    kstats = jnp.concatenate((jnp.stack((
        jnp.sum(jnp.where(live, n_seq_doc, 0)),
        jnp.sum(jnp.where(live, jnp.minimum(dups, seq_counts), 0)),
        I32(0))), rebalance_stats))
    return (seq_state, map_state, merge_state, matrix_state, tree_state,
            n_seq, first, last, msn_doc, tree_overflow, text_overflow,
            kstats)


# Donated serving ticks must never compile through the persistent cache
# (jaxlib 0.4.37 double-frees donated buffers on the second run of a
# cache-DESERIALIZED executable — compile_cache.bypass docstring).
_mixed_tick = compile_cache.uncached(_mixed_tick)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _storm_tick(seq_state: seqk.SequencerState, map_state: mk.MapState,
                slot, cseq0, ref, ts, seq_counts,
                map_gather, words, map_counts):
    """deli ticket + merger fold fused into one device program.

    seq inputs are [B_seq] vectors (per-doc constants; 4 bytes/op of
    words is the only [B, K] transfer). The deli leg is the CLOSED-FORM
    storm ticket (:func:`sequencer.storm_tickets`): on the storm shape
    the per-op scan collapses to O(1)-per-doc algebra, so the fused tick
    is merger-bound, not sequencer-bound (VERDICT r3 item 3).
    ``map_gather`` maps each map row to its document's sequencer row so
    the ticket seqs feed the map fold without leaving the device.
    """
    seq_before = seq_state.seq
    seq_state, dups, n_seq_doc, msn_doc = seqk.storm_tickets(
        seq_state, slot, cseq0, ref, ts, seq_counts)

    dups_for = dups[map_gather]
    nseq_for = n_seq_doc[map_gather]
    seq0_for = seq_before[map_gather]
    lo = dups_for
    hi = jnp.minimum(dups_for + nseq_for, map_counts)
    map_state = _map_leg(map_state, words, lo, hi, seq0_for)

    n_seq = nseq_for
    first = jnp.where(n_seq > 0, seq0_for + 1, oc.INT32_MAX)
    last = jnp.where(n_seq > 0, seq0_for + n_seq, 0)
    msn = jnp.where(map_counts > 0, msn_doc[map_gather], 0)
    # Per-doc poison sentinel (summary-drift / invariant violation): a
    # healthy map row never carries a vseq above its doc's post-tick seq,
    # and present slots never hold negative vseq/value. One cheap VPU
    # reduce per row that rides the existing readback batch — the
    # quarantine plane's detection input (harvest freezes flagged docs
    # only; the rest of the batch keeps serving).
    seq_after = seq_state.seq[map_gather]
    drift = jnp.max(jnp.where(map_state.present, map_state.vseq, -1),
                    axis=1) > seq_after
    corrupt = jnp.any(map_state.present
                      & ((map_state.vseq < 0) | (map_state.value < 0)),
                      axis=1)
    bad = drift | corrupt
    # Device-side kernel counter plane: a few VPU reduces packed into ONE
    # tiny i32[KSTATS_WIDTH] output riding the tick's EXISTING readback
    # batch (zero extra device syncs) — total sequenced, duplicate-
    # dropped ops, and sentinel-tripped docs; the rebalance cells stay 0
    # on this map-only leg (the block-table counters live in the mixed
    # tick — shared index layout, see KSTAT_*). Rows with no batch this
    # tick gather row 0's ticket values, so every reduce masks on
    # map_counts > 0.
    live = map_counts > 0
    kstats = jnp.stack((
        jnp.sum(jnp.where(live, n_seq, 0)),
        jnp.sum(jnp.where(live, jnp.minimum(dups_for, map_counts), 0)),
        jnp.sum(jnp.where(live, bad, False).astype(I32)),
        I32(0), I32(0)))
    return seq_state, map_state, n_seq, first, last, msn, bad, kstats


_storm_tick = compile_cache.uncached(_storm_tick)


#: Format version stamped on every storm WAL tick header ("v") and on
#: storm snapshot records ("format_version"). Version 0 = the pre-stamp
#: round-7 format (no field); readers accept 0..CURRENT and refuse
#: anything newer (a downgrade must fail loudly, not misparse).
#: v2 (round 15) adds mega-doc lifecycle CONTROL records (docs-less tick
#: headers carrying an "mg" event) and lane-id tick entries — a
#: rolled-back binary must refuse rather than silently drop a promotion.
#: v3 (round 18) adds history-plane CONTROL records (docs-less tick
#: headers carrying an "hp" event: branch forks, trimmed-tick fillers)
#: and a snapshot "history" field — a rolled-back binary must refuse
#: rather than silently drop a branch.
STORM_WAL_VERSION = 3
STORM_SNAPSHOT_VERSION = 3


def choose_pipeline_depth(attribution: dict, current: int = 1) -> int:
    """Pick the serving pipeline depth from OBSERVED stage attribution
    (the round-15 adaptive-depth satellite). BENCH_r14's depth-scaling
    rows are the evidence: at the 10k-doc shape the group fsync is
    commensurate with the dispatch (wal_commit_wait 0.52 vs
    device_dispatch 0.41 of the tick) and overlapping them wins 1.48x;
    at the 2048-doc shape blobs are small, the fsync is cheap, and the
    SERIAL tick wins (pipelining pays a staging generation + lagged acks
    for nothing). So: commit-wait under a quarter of the dispatch time
    -> depth 0 (serial); at least half -> depth >= 1 (overlap); the band
    between is hysteresis (keep the current depth — flapping would
    resize the staging ring every window). Needs >= 8 ticks of ledger
    window to act; returns ``current`` until then."""
    win = attribution.get("_window", {})
    if win.get("ticks", 0) < 8:
        return current
    commit = attribution.get("wal_commit_wait", {}).get("total_ms", 0.0)
    dispatch = attribution.get("device_dispatch", {}).get("total_ms", 0.0)
    if dispatch <= 0.0:
        return current
    ratio = commit / dispatch
    if ratio < 0.25:
        return 0
    if ratio >= 0.5:
        return max(current, 1)
    return current


class StormController:
    """Buffers storm frames and runs the fused tick over the REAL hosts:
    the service's batched deli (KernelSequencerHost) and merge host
    (KernelMergeHost map rows) — the storm path and the per-op path share
    one sequencer state and one map state per document.

    Overload behavior (the graceful-degradation tentpole): the inbound
    frame queue is bounded (``max_pending_docs``) and an optional
    :class:`~fluidframework_tpu.server.riddler.AdmissionController`
    gates the tick ingress; refused frames get a busy-nack carrying
    ``retry_after_s`` instead of queueing without bound. A per-doc
    poison (device sentinel tripping on a tick output) quarantines ONLY
    that document — its in-flight ops nack retryable, catch-up reads
    keep serving from the (poison-free) durable records with
    :meth:`quarantined_map_entries` as the server-side scalar fold, and
    :meth:`readmit_doc` rebuilds it from snapshot + WAL replay while
    every other doc keeps full-rate serving. A WAL whose fsync breaker
    opens degrades the controller to read-only broadcast mode until the
    half-open probes heal it."""

    #: Per-op count sanity bound (one doc's batch within one frame).
    MAX_COUNT = 1 << 16

    def __init__(self, service, seq_host: KernelSequencerHost,
                 merge_host: KernelMergeHost, datastore: str = "default",
                 channel: str = "root",
                 flush_threshold_docs: int = 4096,
                 max_key_slots: int = 64,
                 pipeline_depth: int | str = 1,
                 spill_dir: str | None = None,
                 durability: str | None = None,
                 snapshots=None,
                 snapshot_interval_ticks: int | None = None,
                 admission=None,
                 max_pending_docs: int | None = None,
                 busy_retry_s: float = 0.05,
                 doc_index_retention_ticks: int | None = None,
                 wal_commit_latency_s: float = 0.0,
                 tenant_weights: dict[str, float] | None = None,
                 tenant_weight_source=None,
                 tick_slot_budget: int | None = None,
                 qos_borrow_fraction: float = 0.5,
                 logger=None) -> None:
        self.service = service
        self.seq_host = seq_host
        self.merge_host = merge_host
        self.datastore = datastore
        self.channel = channel
        self.flush_threshold_docs = flush_threshold_docs
        # Storm words address key slots directly; the map state must be
        # wide enough BEFORE any tick (out-of-range slots would silently
        # no-op on device while the durable history replays them).
        self.max_key_slots = min(1024, max_key_slots)  # 10-bit slot field
        if merge_host._map_slots < self.max_key_slots:
            merge_host._grow_map_slots(self.max_key_slots)
        self._frames: list[_Frame] = []
        self._pending_docs = 0
        # Bounded cohort LRU: (membership_gen, ((doc, client), ...)) ->
        # resolved (seq_rows, slots, map_rows) arrays. Residency churn
        # guarantees ALTERNATING cohorts (hydrations rotate the doc set),
        # so the old single-entry cache thrashed every tick — a small LRU
        # keeps each live cohort's resolution warm, and the hit/miss
        # counters (storm.cohort_cache.*) make a thrash observable.
        from ..utils import CountedLRU
        self._cohort_cache = CountedLRU(
            8, registry=merge_host.metrics, prefix="storm.cohort_cache")
        self._tick_counter = 0  # tick blob index
        # Tick words blobs: the bulk of the scriptorium payload. With a
        # spill dir they ride the disk WAL (the Mongo-storage analog —
        # serving-host RSS stays bounded however long the run, VERDICT
        # r4 weak #6); without one they stay in process memory like the
        # rest of the in-memory StateStore.
        self._tick_blobs: dict[int, bytes] = {}
        # doc -> [(first_seq, last_seq, tick_id)] for ticks that
        # sequenced ops — the compact in-RAM index over the tick blobs.
        self._doc_ticks: dict[str, list[tuple[int, int, int]]] = {}
        # Durability mode of the tick WAL (CRC-framed OpLog either way):
        #   "group" — async group-commit writer (durable_store.
        #             GroupCommitLog): the harvest path pays a queue put;
        #             fsyncs batch on the writer thread; ACKS ARE WITHHELD
        #             until the durability watermark passes the tick, so
        #             an acked op can never be lost to a crash.
        #   "sync"  — append + fdatasync inline per tick (the maximally
        #             conservative shape; the bench durability column).
        #   "none"  — append only, no fsync (the round-5 behavior: a
        #             process kill keeps the data, a host crash may not).
        # None (default) = "group" when a spill dir is given, else no WAL.
        # An EXPLICIT "group"/"sync" without a spill dir is a
        # misconfiguration and must fail loudly — silently serving
        # without the acked-durable contract the caller asked for would
        # void the one guarantee this layer exists to give.
        if durability not in ("group", "sync", "none", None):
            raise ValueError(f"unknown durability mode {durability!r}")
        if durability in ("group", "sync") and spill_dir is None:
            raise ValueError(
                f"durability={durability!r} needs a spill_dir (the WAL "
                "lives there); pass durability='none' for WAL-less "
                "serving")
        if durability is None:
            durability = "group" if spill_dir is not None else "none"
        self.durability = durability
        self._blob_log = None
        self._group_wal = None
        self._spill_path = None
        # (tick_id, [(frame, ack payload)], harvest_ns, ledger record)
        # awaiting the durability watermark — drained in tick order on
        # the serving thread.
        self._unacked: list[tuple[int, list, int, dict | None]] = []
        if spill_dir is not None:
            import pathlib

            from ..native import OpLog
            from .durable_store import GroupCommitLog
            root = pathlib.Path(spill_dir)
            root.mkdir(parents=True, exist_ok=True)
            path = root / "storm_tick_words.log"
            self._spill_path = path  # trim_tick_blobs rewrite target
            if durability == "group":
                # commit_latency_s models a replicated durable log's
                # quorum round trip (bench regime); 0 = local disk.
                self._group_wal = GroupCommitLog(
                    path, commit_latency_s=wal_commit_latency_s)
                self._blob_log = self._group_wal
            else:
                self._blob_log = OpLog(path)
            # Restart/reuse recovery: the RAM (first, last, tick) index
            # and the tick counter rebuild from the journaled blobs, so
            # catch-up reads survive a serving-host restart and a reused
            # spill dir cannot alias fresh tick ids onto stale blobs.
            for tick_id in range(len(self._blob_log)):
                header, _off = self._parse_header(
                    bytes(self._blob_log.read(tick_id)))
                for entry in header["docs"]:
                    doc, _c, _c0, _r, _n, ns, fs, ls, _m, _w = entry
                    if ns > 0:
                        self._doc_ticks.setdefault(doc, []).append(
                            (fs, ls, tick_id))
            self._tick_counter = len(self._blob_log)
        # Device-pool snapshot backend (GitSnapshotStore surface). With an
        # interval, flush() checkpoints every N ticks; recover() restores
        # the head + replays the WAL tail (see checkpoint()/recover()).
        self.snapshots = snapshots
        self.snapshot_interval_ticks = snapshot_interval_ticks
        self._last_checkpoint_tick = self._tick_counter
        self._in_checkpoint = False
        # WAL-replay mode (recover()): reuse THE serving tick verbatim but
        # pin timestamps to the recorded ones and skip re-persisting.
        self._replay = False
        self._replay_ts: int | None = None
        self._trim_gate = _TrimGate()
        # Tick-ingress admission (the alfred/deli throttle seam moved to
        # where batching amplifies it): a bounded inbound queue + token
        # buckets; refusals are busy-nacks, never silent drops or OOM.
        self.admission = admission
        self.max_pending_docs = max_pending_docs
        self.busy_retry_s = busy_retry_s
        if admission is not None and max_pending_docs is not None:
            admission.add_pressure_probe(
                lambda: self._pending_docs / max(1, self.max_pending_docs))
        # Multi-tenant QoS plane (server/qos.py, the round-17 tentpole):
        # deficit-weighted fair tick composition over per-tenant pending
        # queues. ``tick_slot_budget`` bounds one tick's doc slots (None
        # = unbounded — composition then only orders, exactly the
        # legacy cohort for single-tenant serving); ``tenant_weights``
        # configures relative shares (unlisted tenants weigh 1.0).
        # Scheduler state (deficits + rotation) rides every
        # multi-tenant tick's WAL header and the snapshot, so recovery
        # resumes composing exactly where the crash stopped.
        # ``tenant_weight_source`` derives weights from tenant RECORDS
        # (riddler paid tiers) for tenants with no explicit config; the
        # resolved weight journals with the scheduler state.
        from .qos import TenantScheduler
        self.qos = TenantScheduler(weights=tenant_weights,
                                   weight_source=tenant_weight_source,
                                   registry=merge_host.metrics)
        self.tick_slot_budget = tick_slot_budget
        # Weighted-shed borrow threshold: a tenant past its weighted
        # pending share still buffers while the GLOBAL queue is below
        # this fraction of max_pending_docs (work conservation); past
        # it, the over-share tenant sheds first.
        self.qos_borrow_fraction = qos_borrow_fraction
        # Quarantine plane: doc -> {"reason", "tick"}. A quarantined doc
        # is frozen out of cohorts (submits nack retryable) and serves
        # reads through the scalar record fold until readmit_doc().
        self.quarantined: dict[str, dict] = {}
        # Tiered hot/cold residency (server/residency.py attaches
        # itself): when set, _admit hydrates cold docs (or busy-nacks a
        # stampede), WAL replay hydrates on first touch, and eviction
        # trims the per-doc bookkeeping below.
        self.residency = None
        # Mega-doc write scale-out (server/megadoc.py attaches itself):
        # promoted docs serve up to L writer frames per tick through
        # per-lane sub-sequencer rows + the host combiner.
        self.megadoc = None
        # History plane (server/history.py attaches itself): time-travel
        # reads off the cold path, named branches journaled as "hp" WAL
        # controls, and the background summarization compactor driven
        # from the flush maintenance cadence below.
        self.history = None
        # Cluster placement (parallel/placement.py attaches a per-host
        # router): when set, frames naming docs another host owns shed
        # with a "moved" nack carrying the owner as ``moved_to`` (the
        # client redials through the reconnect/backoff path), and docs
        # mid-migration shed "migrating" with a retry hint — never
        # sequenced on the wrong host, never silently dropped.
        self.placement = None
        # Replication plane (server/replication.py attaches itself):
        # when set, client acks gate on min(durable, REPLICATED)
        # watermarks — an acked op survived a follower quorum, not just
        # this host's disk — and a fenced (demoted) plane sheds every
        # frame with a "moved" nack naming the promoted incarnation.
        self.replication = None
        self._in_round = False  # mid-_flush_round (evictions refuse)
        # Opt-in retention for the per-doc (first, last, tick) index:
        # entries whose tick falls below ``tick_counter - retention``
        # drop at harvest. Mirrors parallel/serving.py's
        # durable_retention_ticks contract — catch-up reads older than
        # the horizon become impossible (clients that far behind reload
        # from a snapshot), and in exchange a long-lived host's index
        # RAM is bounded by the retention window, not total history.
        # None (default) keeps the full index.
        self.doc_index_retention_ticks = doc_index_retention_ticks
        #: Ticks each doc participated in (telemetry: the zero-lost-ticks
        #: invariant for a quarantined doc's batch peers asserts on this).
        self.doc_tick_counts: dict[str, int] = {}
        self.stats = {"ticks": 0, "sequenced_ops": 0, "submitted_ops": 0,
                      "nacked_or_ignored_ops": 0,
                      "shed_frames": 0, "shed_ops": 0,
                      "quarantined_docs": 0, "readmitted_docs": 0,
                      "degraded_rejects": 0, "quorum_rejects": 0}
        self.tick_seconds: list[float] = []  # submit→harvest per round
        self.harvest_intervals: list[float] = []  # completion cadence
        # Observability plane (the round-10 tentpole): one fixed-shape
        # stage record per tick into a ring buffer + per-stage Histograms
        # in the shared registry (alfred get_metrics exports them; the
        # monitor renders the attribution bar), and a per-op trace joiner
        # for frames that carry a sampled trace id ("tc" header field).
        from ..utils import NullLogger, StageLedger, TraceSpans
        self.logger = logger if logger is not None else NullLogger()
        self.ledger = StageLedger(registry=merge_host.metrics,
                                  prefix="storm.stage")
        self.tracer = TraceSpans(logger=self.logger)
        self._trace_seq = 0  # per-submission tracer-key disambiguator
        # Server-side sampling cap: the CLIENT picks which frames carry
        # a trace id, but one connection stamping every frame must not
        # commandeer the serving thread's tracer (hop observes, span
        # ring) — past this many traced frames per tick round, extra
        # trace ids are ignored (the frame still serves normally).
        self.max_traces_per_tick = 64
        self._traced_pending = 0
        # ingress decode / admission ns spent on frames buffered toward
        # the NEXT tick (consumed by its ledger record at flush).
        self._staged_ns = {"ingress_decode": 0, "admission": 0}
        # Depth-N pipeline (SURVEY §7 hard part (c), round-14 tentpole):
        # up to N ticks stay in flight; each round HARVESTS the due tick
        # BEFORE staging the next one, so tick N's WAL append (and its
        # group fsync, on the writer thread) starts as soon as N's
        # readback lands and then runs CONCURRENT with tick N+1's
        # scatter + device dispatch — the two dominant stages of the
        # durable tick (BENCH_r10: wal_commit_wait 0.52 + device_dispatch
        # 0.41, formerly back-to-back). Acks stay withheld on the durable
        # watermark exactly as before — they lag dispatch by ≤ depth
        # ticks, which is why flow-controlled senders size their window
        # ≥ depth + 1. Depth 1 is the default; depth 0 is the serial
        # fallback (dispatch → readback → append → fsync barrier → ack,
        # per round — the pre-pipelining shape, kept as the A/B twin and
        # for request-response senders that gate on every ack).
        # pipeline_depth="auto" (the round-15 adaptive-depth satellite):
        # start overlapped and re-decide from the ledger's OBSERVED
        # wal_commit_wait vs device_dispatch shares every adaptation
        # window (choose_pipeline_depth) — the BENCH_r14 depth-scaling
        # rows showed the serial tick wins exactly where the fsync is
        # cheap, which no static constant can know up front.
        self._auto_depth = pipeline_depth == "auto"
        self._depth_adapted_at = 0
        self.depth_adapt_every = 64  # ticks between adaptation checks
        self.pipeline_depth = 1 if self._auto_depth \
            else max(0, pipeline_depth)
        self._inflight: list[dict] = []
        self._last_harvest: float | None = None
        # Monotonic-ns completion of the last NON-replay harvest: the
        # ledger's wall-clock slice per tick (cadence) derives from it.
        self._last_harvest_done_ns: int | None = None
        # Host staging generations (double/N-buffered): the scatter
        # arrays a dispatched tick's device transfer may still alias are
        # never the ones the next round writes — see _staging_gen.
        self._staging: list[dict | None] = [None] * (self.pipeline_depth
                                                     + 1)
        self._staging_idx = 0
        merge_host.metrics.gauge("storm.pipeline.depth").set(
            self.pipeline_depth)
        service.storm = self

    # -- front-door entry ------------------------------------------------------

    def submit_frame(self, push: Callable[[dict], None] | None,
                     header: dict, payload: memoryview,
                     tenant_id: str = "default",
                     client_id: str | None = None,
                     ingress_ns: int | None = None) -> None:
        """One decoded storm frame from a session; ack is pushed after the
        tick that sequences it. Malformed frames raise ValueError BEFORE
        anything is buffered — a bad frame must fail alone, never poison
        co-buffered frames from other sessions.

        ``tenant_id``/``client_id`` are the admission identities and must
        come from the SESSION (token-validated tenant, service-assigned
        client id) — never from the frame header, which the client
        controls (a self-stamped tenant would mint itself a fresh bucket
        per frame).

        ``ingress_ns`` is the transport's receive timestamp
        (``time.monotonic_ns``), stamped BEFORE the codec decode so the
        ledger's ingress_decode split covers it (None = entry here); a
        frame whose header carries a sampled trace id (``"tc"``) gets
        its ingress/admit hops marked on the controller's tracer."""
        if ingress_ns is None:
            ingress_ns = time.monotonic_ns()
        entries = header.get("docs")
        if not isinstance(entries, list) or not entries:
            raise ValueError("storm frame without docs")
        docs: list[tuple[str, str, int, int, int]] = []
        seen: set[str] = set()
        for entry in entries:
            if not (isinstance(entry, (list, tuple)) and len(entry) == 5):
                raise ValueError(f"bad storm doc entry: {entry!r}")
            # NB the entry's writer id must NOT rebind the ``client_id``
            # parameter — admission below keys on the SESSION identity,
            # and a shadowing loop variable would hand the throttle a
            # client-chosen string (fresh token bucket per frame).
            doc_id, doc_client, cseq0, ref_seq, count = entry
            count = int(count)
            if not 0 < count <= self.MAX_COUNT:
                raise ValueError(f"bad storm count {count} for {doc_id!r}")
            if doc_id in seen:
                # One sequencer row per doc per tick: the numpy scatter is
                # last-writer-wins, so an in-frame repeat would silently
                # drop the first batch while acking it as sequenced.
                raise ValueError(f"doc {doc_id!r} repeats within one frame")
            seen.add(doc_id)
            docs.append((str(doc_id), str(doc_client), int(cseq0),
                         int(ref_seq), count))
        # Columnar from here down: ONE payload view + per-doc count/meta
        # arrays — no per-doc np.frombuffer, no byte copy (the words view
        # aliases the receive buffer all the way into the tick scatter).
        meta = np.array([entry[2:] for entry in docs], np.int32)
        counts = meta[:, 2]
        offset = int(counts.sum())
        if offset * 4 > len(payload):
            raise ValueError("storm payload shorter than doc counts")
        words = np.frombuffer(payload, np.uint32, offset)
        max_slot = int((words & np.uint32(0xFFC)).max()) >> 2 \
            if offset else 0
        if max_slot >= self.max_key_slots:
            raise ValueError(
                f"storm key slot {max_slot} >= max_key_slots "
                f"{self.max_key_slots}")
        # Admission gates run AFTER validation (a malformed frame is the
        # sender's error, not overload) and only on live traffic — replay
        # (recovery / readmit) re-runs already-admitted history.
        # The tracer key pairs the client's id with a PER-SUBMISSION
        # counter: clients choose their trace ids independently (two
        # connections sampling the same small integer must never
        # interleave marks on one span), and a shed frame's orphaned
        # marks can never be joined by a later frame reusing the id.
        # The ack carries back the client's raw id (_stamp_trace_ack
        # unpacks the tuple).
        tc = None if self._replay else trace_context(header)
        if not isinstance(tc, (int, str)):
            tc = None  # the field is client-opaque JSON; the tracer
            # keys a dict on it, so unhashable shapes are ignored — a
            # valid frame must never be nacked over its trace id.
        trace = None
        staged = (0, 0)
        t_validated = time.monotonic_ns()
        if not self._replay:
            retry = self._admit(push, header, docs, offset,
                                tenant_id, client_id)
            t_admitted = time.monotonic_ns()
            if retry is not None:
                return  # shed: its decode/admit ns never reaches a tick
            # Charged only once the frame is BUFFERED — shed frames'
            # time must not inflate a surviving tick's attribution (a
            # frame shed LATER, at quarantine, refunds via staged_ns).
            # The trace slot likewise allocates only now: a traced-but-
            # shed frame must not consume the per-tick cap (tracing
            # would starve during exactly the overload it should
            # diagnose).
            staged = (t_validated - ingress_ns, t_admitted - t_validated)
            self._staged_ns["ingress_decode"] += staged[0]
            self._staged_ns["admission"] += staged[1]
            if tc is not None \
                    and self._traced_pending < self.max_traces_per_tick:
                trace = (tc, self._trace_seq)
                self._trace_seq += 1
                self._traced_pending += 1
                self.tracer.mark(trace, "ingress", ingress_ns)
                self.tracer.mark(trace, "admit", t_admitted)
        # Mega-doc ingress: promoted-doc entries are rewritten to their
        # writers' LANE sub-doc ids (stateless hash — up to L writer
        # frames of one doc become DISJOINT cohort members and serve in
        # ONE tick). Doc-level sequencing decisions wait for cohort
        # selection (decide_frame) so doc-seq order == WAL order ==
        # replay order. Admission above ran on the PARENT ids.
        mega = None
        if self.megadoc is not None and not self._replay:
            self.megadoc.observe_writers(docs)
            mega = self.megadoc.ingress_frame(docs)
        self._frames.append(_Frame(push, header.get("rid"), docs, words,
                                   counts, meta, trace, staged, mega,
                                   tenant_id, ingress_ns))
        self._pending_docs += len(docs)
        self.stats["submitted_ops"] += offset
        if not self._replay:
            self.qos.note_submitted(tenant_id, offset)
            self.qos.note_buffered(tenant_id, len(docs))
            if tenant_id != "default":
                # Placement input (multi-tenant only — the single-tenant
                # hot path stays untouched): which tenant owns each doc.
                self.qos.note_doc_tenants(tenant_id,
                                          (d for d, *_ in docs))
        if self._pending_docs >= self.flush_threshold_docs:
            # Threshold-triggered: only run FULL rounds; a partial tail
            # (next tick's early frames) waits for its cohort instead of
            # fragmenting into tiny device ticks.
            self.flush(force=False)

    def _admit(self, push, header: dict, docs: list, n_ops: int,
               tenant_id: str, client_id: str | None) -> float | None:
        """Shed checks for one validated frame, in deterministic order:
        quarantine, degraded (WAL breaker open), bounded queue, token
        buckets. A refusal pushes ONE busy-nack with ``retry_after_s``
        and returns the hint; None admits."""
        if self.replication is not None and self.replication.fenced:
            # Demoted ex-leader (a follower promoted over this
            # incarnation): EVERY frame sheds with the new leader as
            # ``moved_to`` — sequencing here would fork the history the
            # promoted incarnation is already extending. Same nack
            # shape as a placement move, so the PR 16 client redial
            # machinery handles both.
            target = self.replication.moved_to
            return self._shed(
                push, header, n_ops, "moved", self.busy_retry_s,
                docs=[d for d, *_ in docs],
                moved_to={d: target for d, *_ in docs})
        if self.placement is not None:
            # Ownership first — the cheapest check, and a frame for a
            # foreign doc must never consume this host's quarantine /
            # queue / token state. Whole-frame refusal (acks are
            # positional per frame); ``moved_to`` names each moved
            # doc's owning host so the client redials it directly.
            moved: dict[str, str] = {}
            frozen = False
            for d, *_ in docs:
                code, owner = self.placement.route(d)
                if code == "moved":
                    moved[d] = owner
                elif code == "migrating":
                    frozen = True
            if frozen:
                # Mid-migration blackout: the doc is between hosts
                # (evict-to-cold → hydrate); the retry hint is the
                # expected blackout window, after which the route
                # resolves to "moved" (or back to this host).
                return self._shed(push, header, n_ops, "migrating",
                                  self.placement.retry_after_s,
                                  docs=[d for d, *_ in docs])
            if moved:
                return self._shed(push, header, n_ops, "moved",
                                  self.placement.retry_after_s,
                                  docs=[d for d, *_ in docs],
                                  moved_to=moved)
        qdocs = [d for d, *_ in docs if d in self.quarantined]
        if qdocs:
            # The WHOLE frame is refused (acks are positional per frame,
            # so it cannot be split): "docs" lists everything dropped,
            # "quarantined" the offending subset — the client resubmits
            # the healthy docs in their own frame immediately and the
            # quarantined ones after readmission.
            return self._shed(push, header, n_ops, "quarantined",
                              self.busy_retry_s,
                              docs=[d for d, *_ in docs],
                              quarantined=qdocs)
        if self.wal_degraded:
            self.stats["degraded_rejects"] += 1
            if self._group_wal.failed:
                # TERMINAL writer death (index skew, bad payload — not a
                # disk that may heal): retrying is pointless; say so
                # instead of promising a cooldown that never ends.
                return self._shed(push, header, n_ops, "wal-failed",
                                  self.busy_retry_s, retryable=False)
            cooldown = self._group_wal.breaker.cooldown_s
            return self._shed(push, header, n_ops, "degraded",
                              max(cooldown, self.busy_retry_s))
        if (self.replication is not None
                and not self.replication.quorum_ok):
            # Follower quorum lost (lease-based failure detector,
            # server/transport.py): writes PARK — admitted and buffered
            # FIFO, never acked, because _flush_round declines rounds —
            # while the outage is young. Past ``park_max_s`` new frames
            # shed with a retry hint instead of growing the parked
            # queue without bound. Either way: never ack-without-quorum.
            deg = self.replication.quorum_degraded_s()
            if deg is not None and deg >= self.replication.park_max_s:
                self.stats["quorum_rejects"] += 1
                return self._shed(
                    push, header, n_ops, "quorum-lost",
                    max(self.busy_retry_s,
                        self.replication.park_max_s / 2))
        if self.max_pending_docs is not None:
            n = len(docs)
            cap = self.qos.pending_cap(tenant_id, self.max_pending_docs)
            # Shed when the GLOBAL bound is hit (nobody may buffer past
            # it), or — weighted shed — when THIS tenant is past its
            # weighted pending share while the global queue is past the
            # borrow threshold: the over-share tenant sheds first, and
            # borrowing beyond the share is free only while the queue
            # is shallow. Either way the busy-nack's retry hint is
            # per-tenant, scaled by the tenant's OWN backlog relative
            # to its share — the abuser backs off hardest.
            over_global = self._pending_docs + n > self.max_pending_docs
            over_share = (
                cap is not None
                and self.qos.pending_docs.get(tenant_id, 0) + n > cap
                and self._pending_docs + n > self.max_pending_docs
                * self.qos_borrow_fraction)
            if over_global or over_share:
                self.qos.note_shed(tenant_id, n_ops)
                return self._shed(
                    push, header, n_ops, "busy",
                    self.qos.shed_hint(tenant_id, self.busy_retry_s,
                                       self.max_pending_docs),
                    tenant=tenant_id)
        if self.admission is not None:
            retry = self.admission.admit_write(tenant_id, client_id,
                                               weight=n_ops)
            if retry is not None:
                return self._shed(push, header, n_ops, "throttled", retry)
        if self.residency is not None:
            cap = self.residency.max_resident
            if cap is not None and len(docs) > cap:
                # TERMINAL: a single frame naming more distinct docs
                # than the pool holds can never be admitted — no amount
                # of eviction makes room while the frame itself excludes
                # every named doc. Say so instead of promising a retry
                # that cannot succeed (the wal-failed precedent).
                return self._shed(push, header, n_ops, "frame-too-wide",
                                  self.busy_retry_s,
                                  docs=[d for d, *_ in docs],
                                  retryable=False)
            # Tiered residency LAST — hydration is the one expensive
            # gate (snapshot read + row restore, and a full pool pays an
            # eviction's durability barrier), so frames the O(1)
            # queue/throttle checks would shed anyway must never reach
            # it. A hydration stampede or a full pool busy-nacks the
            # WHOLE frame with the bucket's laddered retry hint —
            # cold-doc storms degrade to queued hydrations, never to
            # pool growth or OOM.
            retry, code = self.residency.admit_docs(
                [d for d, *_ in docs])
            if retry is not None:
                return self._shed(push, header, n_ops, code, retry,
                                  docs=[d for d, *_ in docs])
        return None

    def _shed(self, push, header: dict, n_ops: int, code: str,
              retry_after_s: float, docs: list | None = None,
              quarantined: list | None = None,
              retryable: bool = True,
              moved_to: dict | None = None,
              tenant: str | None = None) -> float:
        self.stats["shed_frames"] += 1
        self.stats["shed_ops"] += n_ops
        self.merge_host.metrics.counter("storm.shed_ops").inc(n_ops)
        if tenant is not None:
            self.merge_host.metrics.counter(
                f"storm.tenant.{tenant}.shed_frames").inc()
        if push is not None:
            nack = {"rid": header.get("rid"), "storm": True,
                    "error": code, "retryable": retryable,
                    "retry_after_s": retry_after_s}
            if docs:
                nack["docs"] = docs  # EVERY doc whose ops were dropped
            if quarantined:
                nack["quarantined"] = quarantined
            if moved_to:
                nack["moved_to"] = moved_to  # doc -> owning host label
            push(nack)
        return retry_after_s

    # -- the tick --------------------------------------------------------------

    def flush(self, force: bool = True) -> None:
        while self._frames and (
                force or self._pending_docs >= self.flush_threshold_docs):
            if not self._flush_round(require_full=not force):
                break
        if force:
            self._harvest()
            if self._group_wal is not None and self._unacked:
                from .durable_store import WalDegradedError
                try:
                    # Drain barrier: a forced flush settles everything, so
                    # withheld acks go out now — after their fsync, never
                    # before (the acked-durable contract).
                    self._group_wal.sync()
                except WalDegradedError:
                    # Fsync breaker open: acks STAY withheld (they are
                    # not durable) and the controller serves read-only —
                    # new writes nack at _admit until the half-open
                    # probes heal the WAL and a later flush drains here.
                    self.merge_host.metrics.counter(
                        "storm.degraded_flushes").inc()
                else:
                    self._drain_durable_acks()
        if (self.snapshot_interval_ticks is not None
                and self.snapshots is not None
                and not self._replay and not self._in_checkpoint
                and not self.wal_degraded and not self.quarantined
                and self._tick_counter - self._last_checkpoint_tick
                >= self.snapshot_interval_ticks):
            self.checkpoint()
        # Maintenance cadence OFF the per-tick path: mega-doc auto
        # promotion/demotion and the adaptive pipeline depth re-decide
        # here (never inside a round), then the RSS arena trim.
        if self.megadoc is not None and not self._replay:
            self.megadoc.maybe_adapt()
        if self.history is not None and not self._replay \
                and not self._in_checkpoint:
            # Summarization compaction cadence (server/history.py): roll
            # long WAL tails into fresh summaries + trim per retention.
            self.history.maybe_compact()
        if self._auto_depth and not self._replay and (
                self.stats["ticks"] - self._depth_adapted_at
                >= self.depth_adapt_every):
            self._depth_adapted_at = self.stats["ticks"]
            self.set_pipeline_depth(choose_pipeline_depth(
                self.ledger.attribution(), self.pipeline_depth))
        # RSS hygiene OFF the per-tick path: at most one arena trim per
        # flush, gated on tick count AND a wall-clock floor (the round-5
        # serving-loop stall suspect — see _TrimGate).
        if self._trim_gate.due(self.stats["ticks"]):
            _malloc_trim()

    def set_pipeline_depth(self, depth: int) -> None:
        """Change the serving pipeline depth between rounds: settle the
        in-flight ticks first (a shrink must not orphan them), then the
        staging-generation ring resizes lazily on the next round."""
        depth = max(0, int(depth))
        if depth == self.pipeline_depth:
            return
        self._harvest()
        self.pipeline_depth = depth
        self.merge_host.metrics.gauge("storm.pipeline.depth").set(depth)

    @property
    def wal_degraded(self) -> bool:
        """True while the WAL writer's fsync circuit breaker is open:
        the controller serves reads and withholds acks, and _admit nacks
        every write with a retryable "degraded" code. Clears itself when
        a half-open probe fsyncs successfully."""
        return (self._group_wal is not None
                and self._group_wal.breaker.is_open)

    @property
    def durable_watermark(self) -> int | None:
        """Ticks proven durable (fsynced): everything below this tick id
        survives a crash. None = serving without a WAL."""
        if self._group_wal is not None:
            return self._group_wal.durable_len
        if self._blob_log is not None:
            return len(self._blob_log) if self.durability == "sync" else 0
        return None

    @property
    def acked_watermark(self) -> int | None:
        """The watermark client acks actually gate on: local durability
        alone without a replication plane, ``min(durable, replicated)``
        with one — an ack then proves the op survives the HOST, not
        just the process. The plane ships synchronously on the WAL
        writer thread, so in the healthy case the two watermarks move
        together and the pipelined tick hides the commit round trip; a
        partitioned quorum freezes the replicated side and acks stay
        withheld (clients resend — the degraded-WAL discipline)."""
        dw = self.durable_watermark
        if dw is not None and self.replication is not None:
            dw = min(dw, self.replication.replicated_len)
        return dw

    def _drain_durable_acks(self) -> None:
        """Push withheld acks whose tick the WAL has fsynced (and the
        follower quorum journaled, when replication is attached) —
        called on the serving thread (harvest / forced flush), never
        the writer thread, so session pushes stay single-threaded."""
        dw = self._group_wal.durable_len
        if self.replication is not None:
            dw = min(dw, self.replication.replicated_len)
        if self._inflight and self._unacked and self._unacked[0][0] < dw:
            # Chaos kill class "fsync-complete-before-readback": tick N
            # is durable and about to ack while a later tick's device
            # work is still in flight (its readback not yet taken).
            # Recovery must replay N byte-identically and must never
            # treat the in-flight tick as acked or durable.
            faults.crashpoint("storm.overlap_fsynced")
        while self._unacked and self._unacked[0][0] < dw:
            _tick, acks, t_harvested, led = self._unacked.pop(0)
            t_drain = time.monotonic_ns()
            if led is not None:
                # The tick's commit-wait: harvest done → fsync watermark
                # passed (the acked-durable latency the ledger attributes).
                self.ledger.amend(led, "wal_commit_wait",
                                  t_drain - t_harvested)
            faults.crashpoint("storm.pre_ack")
            for frame, payload in acks:
                payload["dw"] = dw
                if frame.trace is not None:
                    self.tracer.mark(frame.trace, "durable", t_drain)
                    self._stamp_trace_ack(frame, payload)
                if frame.t0:
                    # Per-tenant SLO surface: submit→durable-ack latency
                    # into the tenant's ack histogram (get_metrics
                    # exports p50/p99; render_tenants renders them).
                    self.qos.observe_ack(frame.tenant,
                                         (t_drain - frame.t0) / 1e9)
                frame.push(payload)

    def _push_synth_acks(self, acks: list, mega_plans: dict) -> None:
        """Deliver acks for a cohort that collapsed to zero descs (every
        entry decided zero-op by the mega combiner). Nothing sequenced —
        but a refseq outcome journaled a state-bearing mark CONTROL
        record, and the client acts on the nack (rebases, advances its
        resend window), so the acked-before-durable discipline applies
        here too: barrier the group commit before pushing. A degraded
        WAL withholds these acks exactly like tick acks (the client
        retries; live and recovered decisions are deterministic either
        way)."""
        from ..protocol.codec import StormAck
        if self._group_wal is not None and not self._replay:
            from .durable_store import WalDegradedError
            try:
                self._group_wal.sync()
            except WalDegradedError:
                return  # not durable: withhold (clients resend)
            if self.replication is not None \
                    and self.replication.replicated_len \
                    < self._group_wal.durable_len:
                # Durable locally but not on the follower quorum: the
                # same withhold discipline, one tier out.
                return
        dw = self.acked_watermark
        for ack_i, (frame, _i0, _i1) in enumerate(acks):
            if frame.push is None:
                continue
            plan = mega_plans.get(ack_i) or []
            rows = np.asarray([v for kind, v in plan if kind == "s"],
                              np.int32).reshape(-1, 4)
            payload = StormAck(frame.rid, rows)
            payload["dw"] = dw
            if frame.trace is not None:
                self._stamp_trace_ack(frame, payload)
            frame.push(payload)

    def _stamp_trace_ack(self, frame: _Frame, payload: dict) -> None:
        """Finish a sampled frame's span at ack transmit: the joined hop
        marks ride the ack header ("tc" + "hops", monotonic ns — clients
        on the same host join their send/rx clocks in), the hop deltas
        feed ``storm.hop.*`` histograms, and the span record goes out
        through the telemetry logger."""
        self.tracer.mark(frame.trace, "ack_tx")
        span = self.tracer.finish(frame.trace)
        if span is None:
            return
        payload[TRACE_KEY] = frame.trace[0]  # the client's raw id
        payload["hops"] = span["hops"]
        metrics = self.merge_host.metrics
        for name, ms in span["deltas_ms"].items():
            metrics.histogram(f"storm.hop.{name}").observe(ms / 1000.0)

    def _flush_round(self, require_full: bool = False) -> bool:
        """One fused tick over every buffered frame, deferring repeat
        frames for the same document to the next round (one descriptor
        per doc row per tick). With ``require_full``, a round whose
        DISJOINT doc set falls short of the tick threshold declines
        (returns False) — pipelined senders whose later ticks arrive
        early must not fragment the cohort into undersized device ticks."""
        import time as _time

        if self.wal_degraded and not self._replay:
            # Breaker open: do NOT advance device state ahead of a WAL
            # that cannot journal it — frames stay queued (new ones are
            # already nacked at _admit) and at most the in-flight
            # pipeline's few ticks still need WAL appends, so the
            # bounded group-commit queue can never overflow into the
            # harvest path mid-outage.
            return False
        if (self.replication is not None and not self._replay
                and not self.replication.quorum_ok):
            # Quorum lost: a tick here would advance device state and
            # journal records no quorum can replicate — the acks would
            # park anyway, and history past the replicated watermark is
            # exactly what a promoted incarnation forks away. Frames
            # stay buffered in arrival order (per-doc FIFO preserved),
            # so the healed quorum sequences the identical history a
            # never-partitioned leader would have.
            self.merge_host.metrics.gauge("repl.parked_docs").set(
                self._pending_docs)
            return False
        round_start = _time.perf_counter()
        queue_depth = self._pending_docs
        frames, self._frames, self._pending_docs = self._frames, [], 0
        # Bus-path ops already admitted must sequence first (per-doc total
        # order is shared between the storm and per-op paths). The
        # in-round flag keeps the pump's idle pass from evicting docs out
        # from under the cohort being assembled (residency.evict refuses
        # while it is set).
        self._in_round = True
        try:
            self.service.pump()
            self.seq_host._flush_pending()
        finally:
            self._in_round = False

        # Tick composition is the QoS seam (server/qos.py): the deficit
        # round robin drains per-tenant queues by weight into the tick's
        # doc slots — an abusive tenant saturates only its own share.
        # The plan keeps the two hard ordering rules: one frame per doc
        # per tick (per-doc FIFO — a colliding frame stays buffered),
        # and the mega FIFO fence (once any frame of a promoted doc is
        # passed over, every LATER frame of that doc is too — the
        # combiner stamps doc seqs in cohort order, and taking a later
        # lane's frame past a deferred earlier one would reorder the
        # doc's total order relative to the single-lane path). A
        # single-tenant compose with no slot budget reduces exactly to
        # the legacy first-come scan.
        if self._replay:
            # Replay never re-composes: the recorded cohort IS the
            # composition (one frame per replayed tick), and scheduler
            # state comes from the tick headers — a synthetic replay
            # frame must not register phantom tenants.
            qplan = {"selected": frames, "kept": [], "charge": {},
                     "slices": {}, "quantum": None}
        else:
            qplan = self.qos.compose(frames, self.tick_slot_budget)
        selected: list[_Frame] = qplan["selected"]
        kept: list[_Frame] = qplan["kept"]
        # A slot budget below the flush threshold caps every cohort
        # under it — a full-budget tick IS a full round then, or
        # threshold-triggered flushing would decline forever.
        full_bar = self.flush_threshold_docs \
            if self.tick_slot_budget is None \
            else min(self.flush_threshold_docs, self.tick_slot_budget)
        if require_full and sum(len(f.docs) for f in selected) \
                < full_bar:
            # Undersized cohort: put everything back; the idle drain (or
            # the cohort completing) will run it. No mega decision has
            # run yet and the scheduler plan was NOT committed, so
            # re-buffering is side-effect free.
            self._frames = frames + self._frames
            self._pending_docs += sum(len(f.docs) for f in frames)
            return False
        self.qos.commit(qplan)
        if not self._replay:
            # Chaos kill class "mid-composition": scheduler state moved
            # (deficits charged, rotation advanced) but the tick neither
            # dispatched nor journaled. Recovery restores the scheduler
            # from the last durable tick's header; the selected frames
            # come back via client resend and recompose deterministically
            # against that state — the single-tenant twin diff proves
            # fairness never changes converged replica state.
            faults.crashpoint("storm.qos_mid_compose")
        # A kept frame's staged decode/admit ns is consumed by THIS
        # round's record (it was already pooled) — zero it on the frame
        # so a later quarantine shed refunds exactly what is still
        # staged, never double-subtracting.
        self._frames.extend(f._replace(staged_ns=(0, 0))
                            for f in kept)
        self._pending_docs += sum(len(f.docs) for f in kept)
        if not self._replay:
            self.qos.reset_pending(self._frames)
        # HARVEST-FIRST (the round-14 pipelining order): settle the due
        # tick BEFORE staging this one, so its readback is taken the
        # moment it matters and its WAL append reaches the writer thread
        # NOW — the group fsync then runs concurrent with this round's
        # scatter + device dispatch instead of queueing behind them
        # (BENCH_r10 measured the two stages back-to-back at 0.52 + 0.41
        # of every durable tick). This also frees the harvested tick's
        # staging generation for reuse below, and it must precede the
        # mega cohort transform: the combiner may journal CONTROL
        # records, which have to land AFTER the due tick's WAL record so
        # replay re-applies mirror updates in live order.
        while len(self._inflight) >= max(1, self.pipeline_depth):
            self._harvest_one(self._inflight.pop(0))
        # WAL replay re-runs the tick with its RECORDED timestamp so the
        # sequencer planes (client last_update) rebuild byte-identically.
        # Computed before cohort assembly: the mega combiner stamps the
        # same clock the device ts plane carries.
        now = (self._replay_ts if self._replay_ts is not None
               else self.service._clock())
        descs: list[tuple[str, str, int, int, int]] = []
        frame_words: list[np.ndarray] = []   # one payload view per frame
        frame_counts: list[np.ndarray] = []
        metas: list[np.ndarray] = []
        acks: list[tuple[_Frame, int, int]] = []  # frame -> desc [i0, i1)
        mega_rows: dict[int, tuple] = {}   # desc idx -> doc-space quad
        mega_plans: dict[int, list] = {}   # ack idx -> per-entry plan
        for frame in selected:
            i0 = len(descs)
            if frame.mega is not None and not self._replay:
                # The combiner: doc-space tickets in cohort admission
                # order (== the single-lane interleaving), dup prefixes
                # trimmed out of the words, zero-op entries dropped with
                # synthesized ack rows.
                (fdesc, fwords, fcounts, fmeta, plan,
                 desc_rows) = self.megadoc.decide_frame(frame, now)
                descs.extend(fdesc)
                frame_words.append(fwords)
                frame_counts.append(fcounts)
                metas.append(fmeta)
                for rel, row in enumerate(desc_rows):
                    if row is not None:
                        mega_rows[i0 + rel] = row
                if len(fdesc) != len(frame.docs):
                    # Dropped entries: the ack is rebuilt positionally
                    # from this plan (synth row or kept-desc index).
                    mega_plans[len(acks)] = [
                        ("s", item.synth) if item.synth is not None
                        else ("l", i0 + item.desc_rel)
                        for item in plan]
            else:
                descs.extend(frame.docs)
                frame_words.append(frame.words)
                frame_counts.append(frame.counts)
                metas.append(frame.meta)
            acks.append((frame, i0, len(descs)))
        if not descs:
            # Every selected entry resolved to a zero-op outcome: no
            # tick to ride — deliver the synthesized acks now (nothing
            # was sequenced, so there is no durability to wait on; the
            # one state-bearing zero-op outcome journaled its own
            # control record in decide_frame).
            self._push_synth_acks(acks, mega_plans)
            return True
        if self._replay and self.megadoc is not None:
            # Replayed lane entries are already cleaned: rebuild the
            # combiner's mirrors + combine logs in desc order (== the
            # order live decisions ran).
            self.megadoc.replay_decide(descs, now)
        if self.megadoc is not None and not self._replay:
            self.megadoc.finish_cohort(descs)
        # Stage ledger: the tick that runs consumes the decode/admission
        # ns staged by its frames' submit_frame calls (a frame DEFERRED
        # to the next round charges the round it was decoded in —
        # attribution, not exact accounting); scatter starts now. Replay
        # rounds record nothing and must not steal ns staged by live
        # frames (readmit replays interleave with serving).
        if self._replay:
            stage_ns = {}
        else:
            stage_ns = dict(self._staged_ns)
            self._staged_ns = {"ingress_decode": 0, "admission": 0}
            self._traced_pending = 0  # next round gets a fresh cap
        t_scatter0 = _time.monotonic_ns()

        seq_host, merge_host = self.seq_host, self.merge_host
        desc_arr = metas[0] if len(metas) == 1 else np.concatenate(metas)
        counts_col = desc_arr[:, 2]
        k = _next_pow2(int(counts_col.max()))

        # Rows + slots (the only per-doc Python work on the hot path).
        # Storm cohorts repeat tick after tick (the same docs stream
        # frames continuously), so the resolved arrays are cached keyed
        # on the exact (doc, client) sequence and the sequencer's
        # membership generation (any join/leave/restore invalidates).
        cohort_key = (seq_host.membership_gen,
                      tuple((d, c) for d, c, *_ in descs))
        cached = self._cohort_cache.get(cohort_key)
        if cached is not None:
            seq_rows, slots, map_rows, mrows, lane_rows = cached
        else:
            seq_rows = np.empty(len(descs), np.int32)
            slots = np.empty(len(descs), np.int32)
            map_rows = np.empty(len(descs), np.int32)
            mrows = []
            for i, (doc, client, _cseq0, _ref, _count) in enumerate(descs):
                row = seq_host._row(doc)
                seq_rows[i] = row
                slots[i] = seq_host._slots[row].get(client,
                                                    seq_host._ghost)
                mrow = self._storm_mrow(doc)
                map_rows[i] = mrow.row
                mrows.append(mrow)
            # Lane sub-sequencer rows keep their cref planes pinned at 0
            # (the doc-space refseq/MSN law lives in the mega combiner);
            # cached alongside the cohort so the per-round forcing below
            # is one vectorized store, not a per-desc string scan.
            lane_rows = (self.megadoc.lane_seq_rows(descs, seq_rows)
                         if self.megadoc is not None
                         else np.empty(0, np.int32))
            self._cohort_cache.put(cohort_key,
                                   (seq_rows, slots, map_rows, mrows,
                                    lane_rows))

        b_seq = seq_host._capacity
        b_map = merge_host._map_capacity
        # Double-buffered staging generations: this round scatters into
        # the IDLE generation while the one a still-in-flight tick's
        # device transfer may alias stays untouched (pipeline_depth + 1
        # generations rotate round-robin; the harvest-first loop above
        # guarantees the generation coming up for reuse was harvested
        # ≥ one round ago). The per-doc vectors re-zero (cheap memsets);
        # the [B, K] words plane deliberately does NOT: every window the
        # tick consumes lies inside the [0, count) prefix freshly
        # scattered for its row this round (rows without a batch have
        # count 0 and an empty ticket window), so stale words from the
        # generation's previous tick are unreachable by construction and
        # the ~MB-scale memset stays off the hot path.
        gen = self._staging_gen(b_seq, b_map, k)
        slot_full = gen["slot"]
        cseq0_full = gen["cseq0"]
        ref_full = gen["ref"]
        seq_counts = gen["seq_counts"]
        ts_full = gen["ts"]
        ts_full.fill(now)
        words_full = gen["words"]
        map_counts = gen["map_counts"]
        gather = gen["gather"]
        slot_full[seq_rows] = slots
        cseq0_full[seq_rows] = desc_arr[:, 0]
        ref_full[seq_rows] = desc_arr[:, 1]
        if lane_rows.size:
            # Live metas already carry 0 here (megadoc._meta_for); the
            # REPLAY path rebuilds metas from WAL entries, whose ref
            # column is the doc-space ref the records need — force the
            # device feed back to the lane contract either way.
            ref_full[lane_rows] = 0
        seq_counts[seq_rows] = desc_arr[:, 2]
        map_counts[map_rows] = desc_arr[:, 2]
        gather[map_rows] = seq_rows
        if counts_col.min() == counts_col.max() == k:
            # Uniform storm (the common shape): one fancy-index scatter
            # PER FRAME, reading straight from each frame's receive
            # buffer (a reshape view) — no np.stack copy, no per-doc
            # Python loop between the socket and the device staging.
            pos = 0
            for fw, fc in zip(frame_words, frame_counts):
                n = len(fc)
                words_full[map_rows[pos:pos + n]] = fw.reshape(n, k)
                pos += n
        else:
            pos = 0
            for fw, fc in zip(frame_words, frame_counts):
                off = 0
                for n in fc.tolist():
                    words_full[map_rows[pos], :n] = fw[off:off + n]
                    off += n
                    pos += 1

        seq_host._host_state = None  # device state is about to move
        t_dispatch0 = _time.monotonic_ns()
        (seq_host._state, merge_host._xstate, n_seq, first, last,
         msn, bad, kstats) = _storm_tick(
            seq_host._state, merge_host._xstate,
            jnp.asarray(slot_full), jnp.asarray(cseq0_full),
            jnp.asarray(ref_full), jnp.asarray(ts_full),
            jnp.asarray(seq_counts), jnp.asarray(gather),
            jnp.asarray(words_full), jnp.asarray(map_counts))
        # Chaos kill class "mid-tick": device state mutated, durable
        # record NOT yet enqueued — the mutation is volatile and must be
        # reconstructible from snapshot + WAL replay + client resend.
        faults.crashpoint("storm.mid_tick")
        # Pipeline: enqueue this tick's device work (and start its
        # device→host copies), then harvest only what has ≥ depth later
        # ticks already in flight behind it.
        rec = dict(
            descs=descs, frame_words=frame_words, counts=counts_col,
            map_rows=map_rows, mrows=mrows,
            acks=acks, now=now, submitted=int(counts_col.sum()),
            out=(n_seq, first, last, msn, bad, kstats), start=round_start,
            start_ns=t_scatter0, depth=self.pipeline_depth,
            stage_ns=stage_ns, queue_depth=queue_depth,
            mega_rows=mega_rows or None, mega_plans=mega_plans or None,
            # Scheduler state AS OF this tick's composition (harvest may
            # run rounds later under pipelining — the WAL header must
            # journal the state the tick was composed against, so replay
            # restores it at the identical point) + the per-tenant slot
            # slices for the windowed attribution ring.
            qos_state=(None if self.qos.is_trivial()
                       else self.qos.export_state()),
            qos_slices=qplan["slices"] or None)
        for out_arr in rec["out"]:
            copy_async = getattr(out_arr, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        t_dispatched = _time.monotonic_ns()
        stage_ns["scatter"] = t_dispatch0 - t_scatter0
        stage_ns["device_dispatch"] = t_dispatched - t_dispatch0
        if not self._replay:
            for frame, _i0, _i1 in acks:
                if frame.trace is not None:
                    self.tracer.mark(frame.trace, "dispatch", t_dispatched)
        self._inflight.append(rec)
        if self._group_wal is not None and not self._replay:
            # Chaos kill class "mid-overlap dispatch": this tick's device
            # work is enqueued while the previous tick's group commit may
            # still be in flight on the writer thread. The previous tick
            # must replay byte-identically from whatever the WAL made
            # durable, and THIS tick (never appended, never acked) must
            # come back only via client resend.
            faults.crashpoint("storm.overlap_dispatch")
        if self.pipeline_depth == 0:
            # Serial fallback: settle this tick NOW — readback, WAL
            # append, the full durability barrier (measured inline as
            # its commit-wait stage) and its acks — before anything else
            # may stage. The conservative pre-pipelining shape (and the
            # A/B twin the pipelined path diffs against).
            self._harvest_one(self._inflight.pop(0))
        elif self._group_wal is not None and self._unacked \
                and not self._replay:
            # Opportunistic NON-blocking drain: a tick whose fsync
            # completed while this round staged and dispatched acks now
            # instead of waiting for the next harvest — the client-side
            # flow-control window is keyed off these acks, so releasing
            # them a round late would stall windowed senders a full
            # cadence.
            self._drain_durable_acks()
        return True

    def _staging_gen(self, b_seq: int, b_map: int, k: int) -> dict:
        """The next idle host staging generation. ``pipeline_depth + 1``
        generations rotate round-robin, so the arrays this round
        scatters into are NEVER ones a still-in-flight tick's device
        transfer may alias (jax.Array transfers on some backends keep a
        view of the host buffer until the computation consumes it) —
        a frame scattered into generation B while generation A is in
        flight must never touch A's device feed. A geometry change
        (capacity growth, a different per-round K) reallocates just the
        generation it lands on; a runtime pipeline_depth change resizes
        the ring."""
        n = self.pipeline_depth + 1
        if len(self._staging) != n:
            self._staging = [None] * n
            self._staging_idx = 0
        self._staging_idx = (self._staging_idx + 1) % n
        gen = self._staging[self._staging_idx]
        if gen is None or gen["shape"] != (b_seq, b_map, k):
            gen = {
                "shape": (b_seq, b_map, k),
                "slot": np.zeros(b_seq, np.int32),
                "cseq0": np.zeros(b_seq, np.int32),
                "ref": np.zeros(b_seq, np.int32),
                "ts": np.zeros(b_seq, np.int32),
                "seq_counts": np.zeros(b_seq, np.int32),
                "words": np.zeros((b_map, k), np.uint32),
                "map_counts": np.zeros(b_map, np.int32),
                "gather": np.zeros(b_map, np.int32),
            }
            self._staging[self._staging_idx] = gen
        else:
            # Re-zero the per-doc vectors only — the words plane's stale
            # content is unreachable (see the _flush_round comment).
            for f in ("slot", "cseq0", "ref", "seq_counts", "map_counts",
                      "gather"):
                gen[f].fill(0)
        return gen

    def idle_drain(self) -> bool:
        """Bounded, NON-blocking idle-path service (the bridge pump's
        no-event branch): release acks whose group commit completed, run
        buffered partial-cohort tails, and harvest an in-flight tick
        whose device results are already materialized. Unlike
        :meth:`flush`, this never blocks on the durability barrier and
        never collapses the pipeline — a flow-controlled client waiting
        out its ack window goes quiet between frames, and forcing a full
        settle on every quiet poll would serialize the server back into
        lockstep dispatch→fsync ticks. Returns True when anything
        progressed."""
        moved = False
        if self._group_wal is not None and self._unacked:
            before = len(self._unacked)
            self._drain_durable_acks()
            moved = len(self._unacked) != before
        if self._frames:
            # A partial tail below the tick threshold: the senders are
            # BLOCKED on these acks (nothing else is coming) — the full
            # settle is the right shape here, exactly as before.
            self.flush()
            return True
        if self._inflight:
            ready = all(
                getattr(arr, "is_ready", lambda: True)()
                for arr in self._inflight[0]["out"])
            if ready:
                self._harvest_one(self._inflight.pop(0))
                moved = True
        if not self._inflight and self._unacked \
                and self._group_wal is not None:
            # Pipeline EMPTY, only the group commit outstanding: a
            # lockstep (ack-gated, unwindowed) sender is blocked on
            # exactly this fsync, and there is nothing in flight the
            # barrier could serialize against — take it, bounded by one
            # group-commit latency, and release the acks now instead of
            # next poll.
            from .durable_store import WalDegradedError
            try:
                self._group_wal.sync()
            except WalDegradedError:
                pass  # degraded: acks stay withheld until healed
            else:
                self._drain_durable_acks()
                moved = True
        return moved

    def _harvest(self) -> None:
        while self._inflight:
            self._harvest_one(self._inflight.pop(0))

    def _harvest_one(self, rec: dict) -> None:
        import time as _time

        t_read0 = _time.monotonic_ns()
        n_seq, first, last, msn, bad, kstats = (np.asarray(a)
                                                for a in rec["out"])
        # Device-side kernel counters (the i32[3] stats plane riding this
        # readback): sequenced / dup-dropped / sentinel docs, device-true.
        kstats = kstats.tolist()
        t_readback = _time.monotonic_ns()
        if self._group_wal is not None and not self._replay:
            # Chaos kill class "readback-before-fsync": this tick's
            # results are read back but its durable record has not yet
            # reached the writer thread — the whole tick is volatile and
            # must be reconstructible from snapshot + WAL replay +
            # client resend (nothing of it was ever acked).
            faults.crashpoint("storm.readback_pre_wal")
        stage_ns = rec.get("stage_ns", {})
        stage_ns["readback"] = t_readback - t_read0
        map_rows = rec["map_rows"]
        # ONE batched gather+pack builds the tick's per-doc ack matrix
        # (n_seq, first, last, msn) — the columnar twin of
        # pack_map_words; the WAL-header lists and every frame's ack are
        # derived from it (columnar → Python exactly once; int() per
        # device element inside the doc loop would dominate the harvest).
        ack_rows = np.stack(
            (n_seq[map_rows], first[map_rows], last[map_rows],
             msn[map_rows]), axis=1).astype(np.int32, copy=False)
        ns_l = ack_rows[:, 0].tolist()
        fs_l = ack_rows[:, 1].tolist()
        ls_l = ack_rows[:, 2].tolist()
        m_l = ack_rows[:, 3].tolist()
        bad_rows = bad[map_rows]
        any_bad = bool(bad_rows.any())
        bad_l = bad_rows.tolist()
        if not self._replay:
            for frame, _i0, _i1 in rec["acks"]:
                if frame.trace is not None:
                    self.tracer.mark(frame.trace, "sequenced", t_readback)
        fanout = self.service.fanout
        viewers = getattr(self.service, "viewers", None)
        if viewers is not None and (self._replay
                                    or not viewers.active_rooms):
            viewers = None
        # Desc indices whose docs have viewer rooms — collected inside
        # the one existing per-desc loop (no second O(descs) pass).
        # Rooms key by the PARENT doc for mega-lane descs (viewer frames
        # must keep flowing while a doc is promoted), publishing the
        # combiner's DOC-space quad instead of the lane-space device row.
        viewer_idx: list[int] = []
        viewer_rooms: dict[int, str] = {}
        megadoc = self.megadoc
        mega_rows_all = rec.get("mega_rows") or {}
        now = rec["now"]
        mrows = rec["mrows"]
        # scriptorium tick record: ONE blob per tick — a json header of
        # every document's columnar record followed by the raw words.
        # RAM keeps only a compact (first_seq, last_seq, tick) triplet
        # per (doc, tick), so the serving host's memory stays bounded by
        # the tick RATE it retains, not the op volume (with a spill dir
        # the blob rides the disk oplog — the Mongo-storage analog).
        tick_id = self._tick_counter
        self._tick_counter += 1
        # The WAL words region is the frames' receive-buffer views,
        # appended as-is; per-doc byte offsets are one cumsum, not a loop.
        counts_col = rec["counts"]
        word_parts: list = rec["frame_words"]
        total_seq = int(sum(ns_l))
        w_offs = np.zeros(len(counts_col), np.int64)
        w_offs[1:] = np.cumsum(counts_col[:-1].astype(np.int64) * 4)
        offsets = w_offs.tolist()
        header_docs = []
        replaying = self._replay
        doc_tick_counts = self.doc_tick_counts
        pubs: list = [] if fanout is not None and not replaying else None
        for i, (doc, client, cseq0, ref, count) in enumerate(rec["descs"]):
            ns, fs, ls, m = ns_l[i], fs_l[i], ls_l[i], m_l[i]
            mrow = mrows[i]
            if ls > mrow.last_seq:
                mrow.last_seq = ls
            header_docs.append([doc, client, cseq0, ref, count,
                                ns, fs, ls, m, offsets[i]])
            if not replaying:
                if ns > 0:
                    dt = self._doc_ticks.setdefault(doc, [])
                    dt.append((fs, ls, tick_id))
                    retention = self.doc_index_retention_ticks
                    if retention is not None and dt[0][2] < (
                            tick_id - retention):
                        # Opt-in index retention (see __init__): drop
                        # entries below the horizon; ticks are appended
                        # in order, so the trim is a prefix cut.
                        horizon = tick_id - retention
                        keep = 0
                        while keep < len(dt) and dt[keep][2] < horizon:
                            keep += 1
                        del dt[:keep]
                # Telemetry for the quarantine blast-radius invariant:
                # batch peers of a quarantined doc lose zero ticks.
                doc_tick_counts[doc] = doc_tick_counts.get(doc, 0) + 1
                if any_bad and bad_l[i] and doc not in self.quarantined:
                    self._quarantine_doc(doc, "sentinel", tick_id)
                # broadcaster: compact tick frame into the pub/sub hop.
                if pubs is not None:
                    pubs.append((doc, b"\x00storm%d:%d:%d" % (fs, ls, m)))
                if viewers is not None and ns > 0:
                    room_doc = doc
                    if megadoc is not None:
                        parent = megadoc.parent_of(doc)
                        if parent is not None:
                            room_doc = parent
                    if viewers.has_viewers(room_doc):
                        viewer_idx.append(i)
                        viewer_rooms[i] = room_doc
        t_assembled = _time.monotonic_ns()
        stage_ns["ack_pack"] = t_assembled - t_readback
        if pubs:
            # O(batch) broadcast: the whole tick's room publishes go down
            # in ONE native call (fanout_publish_batch) — never one
            # Python write per subscriber connection.
            batch_pub = getattr(fanout, "publish_batch", None)
            if batch_pub is not None:
                batch_pub(pubs)
            else:  # duck-typed fanout without the batch surface
                for room, body in pubs:
                    fanout.publish(room, body)
        # Viewer plane: docs with viewer rooms get this tick's broadcast
        # frame (sequenced window + raw words) serialized ONCE per doc
        # and fanned out in one batched publish — encodes-per-tick ==
        # hot docs with viewers, independent of viewer count (the
        # serialize-once invariant BENCH_r13 pins). Words resolve
        # straight from each frame's receive-buffer view (the same
        # positional layout the WAL appends); only frames CONTAINING a
        # viewer doc pay an offsets walk — a 10k-doc tick with one
        # viewer room touches one frame, not every desc.
        if viewer_idx:
            import bisect
            frame_words = rec["frame_words"]
            items = []
            for f_idx, (_frame, i0, i1) in enumerate(rec["acks"]):
                lo = bisect.bisect_left(viewer_idx, i0)
                hi = bisect.bisect_left(viewer_idx, i1)
                if lo == hi:
                    continue  # no viewer docs in this frame
                fcounts = counts_col[i0:i1].tolist()
                target = viewer_idx[lo]
                off = 0
                for local, count in enumerate(fcounts):
                    gi = i0 + local
                    if gi == target:
                        words = frame_words[f_idx][off:off + count]
                        # Lane descs broadcast the combiner's doc-space
                        # quad (the same rewrite the client ack gets);
                        # viewers of a promoted doc see continuous doc
                        # seq windows, never lane-space ones.
                        quad = mega_rows_all.get(gi) or (
                            ns_l[gi], fs_l[gi], ls_l[gi], m_l[gi])
                        items.append((viewer_rooms[gi], quad[0],
                                      quad[1], quad[2], quad[3],
                                      count, words.tobytes()))
                        lo += 1
                        if lo == hi:
                            break
                        target = viewer_idx[lo]
                    off += count
            viewers.publish_ticks(items)
        t_fanout = _time.monotonic_ns()
        stage_ns["fanout_publish"] = t_fanout - t_assembled
        import json as _json
        import struct as _struct

        hdr: dict = {"v": STORM_WAL_VERSION, "ts": now,
                     "docs": header_docs}
        if rec.get("qos_state") is not None:
            # Multi-tenant scheduler state as of this tick's composition
            # (single-tenant headers stay byte-compatible with every
            # pre-QoS reader/golden — the field simply never appears).
            # Replay imports it tick by tick, so a recovered host's
            # deficits equal the crashed host's at the durable frontier.
            hdr["qos"] = rec["qos_state"]
        header = _json.dumps(hdr, separators=(",", ":")).encode()
        prefix = _struct.pack("<I", len(header)) + header
        if self._replay:
            pass  # the blob IS the replay source; never re-persist it
        elif self._group_wal is not None:
            # Async group commit: the hot path pays ONE queue put; the
            # join + CRC + append + fsync all run on the writer thread.
            # The tick's acks are withheld until the durability watermark
            # passes it (_drain_durable_acks) — the sync per-tick blob
            # write this replaces was the round-5 regression suspect.
            idx = self._group_wal.append([prefix, *word_parts])
            assert idx == tick_id, (idx, tick_id)
            if self.pipeline_depth == 0 and not self._replay:
                # Serial fallback: the durability barrier is tick time
                # ON this thread — nothing overlaps it — so it is
                # measured directly as the commit-wait stage and the
                # tick's wall-clock slice covers it (no amend-at-drain;
                # the ledger must never report phantom overlap for a
                # genuinely sequential tick).
                from .durable_store import WalDegradedError
                t_sync0 = _time.monotonic_ns()
                try:
                    self._group_wal.sync()
                except WalDegradedError:
                    # Breaker open: acks stay withheld (not durable);
                    # _admit is already shedding new writes.
                    self.merge_host.metrics.counter(
                        "storm.degraded_flushes").inc()
                stage_ns["wal_commit_wait"] = (_time.monotonic_ns()
                                               - t_sync0)
        elif self._blob_log is not None:
            blob_bytes = prefix + b"".join(
                bytes(memoryview(p)) for p in word_parts)
            idx = self._blob_log.append(blob_bytes)
            assert idx == tick_id, (idx, tick_id)
            if self.durability == "sync":
                t_sync0 = _time.monotonic_ns()
                self._blob_log.sync()
                stage_ns["wal_commit_wait"] = (_time.monotonic_ns()
                                               - t_sync0)
        else:
            self._tick_blobs[tick_id] = prefix + b"".join(
                bytes(memoryview(p)) for p in word_parts)
        t_wal = _time.monotonic_ns()
        stage_ns["wal_append"] = (t_wal - t_fanout
                                  - stage_ns.get("wal_commit_wait", 0))
        # Stats BEFORE acks: once an ack leaves the process, this host's
        # bookkeeping must already reflect the tick (clients/tests react
        # to acks immediately).
        self.stats["ticks"] += 1
        self.stats["sequenced_ops"] += total_seq
        self.stats["nacked_or_ignored_ops"] += rec["submitted"] - total_seq
        # Storm ops are serving-path device ops: count them in the merge
        # host's routing stats so scalar_fraction spans BOTH ingest paths.
        self.merge_host.stats["device_ops"] += total_seq
        self.merge_host.metrics.counter("storm.sequenced_ops").inc(total_seq)
        # Device-true counters from the kstats plane (vs the host-derived
        # stats above — a drift between the two is itself a signal).
        kmetrics = self.merge_host.metrics
        kmetrics.counter("storm.device.sequenced_ops").inc(kstats[0])
        kmetrics.counter("storm.device.dup_ops").inc(kstats[1])
        kmetrics.counter("storm.device.sentinel_docs").inc(kstats[2])
        # Block-table rebalance attribution (KSTAT_REBALANCE_FIRED /
        # KSTAT_BLOCKS_TOUCHED): 0 on this map-only path by layout; the
        # counters move wherever the mixed/text tick harvests through
        # the same indices, and tools/monitor.py renders the fire rate.
        kmetrics.counter("storm.device.rebalance_fired").inc(
            kstats[KSTAT_REBALANCE_FIRED])
        kmetrics.counter("storm.device.blocks_touched").inc(
            kstats[KSTAT_BLOCKS_TOUCHED])
        if not replaying and rec.get("qos_slices"):
            # Per-tenant slice of this tick: doc slots from the compose
            # plan, sequenced ops from the harvested ack matrix — the
            # windowed share attribution render_tenants reads.
            seq_by_t: dict[str, int] = {}
            for frame, i0, i1 in rec["acks"]:
                seq_by_t[frame.tenant] = seq_by_t.get(frame.tenant, 0) \
                    + int(sum(ns_l[i0:i1]))
            self.qos.note_tick(tick_id, rec["qos_slices"], seq_by_t)
        done = _time.perf_counter()
        self.tick_seconds.append(done - rec["start"])
        if self._last_harvest is not None:
            self.harvest_intervals.append(done - self._last_harvest)
        self._last_harvest = done
        # Mega combiner egress: lane descs' device rows carry LANE-space
        # seqs (what the WAL header above recorded — reads translate);
        # the CLIENT sees doc-space quads, pre-decided by the combiner.
        # The device count must agree with the decision (cleaned lane
        # batches sequence in full by construction) — a drift here means
        # the lane contract broke, which must fail loudly, not misack.
        mega_rows_rec = rec.get("mega_rows")
        if mega_rows_rec:
            if not replaying:
                self.megadoc.note_harvest(rec["descs"])
            for gi, row in mega_rows_rec.items():
                if ns_l[gi] != row[0]:
                    raise AssertionError(
                        f"mega lane desc {rec['descs'][gi][:2]} sequenced "
                        f"{ns_l[gi]} ops on device, combiner decided "
                        f"{row[0]}")
                ack_rows[gi] = row
        elif self.megadoc is not None and not replaying:
            self.megadoc.note_harvest(rec["descs"])
        # Each frame's ack is a contiguous row slice of the tick's ack
        # matrix — a StormAck that session push paths binary-encode
        # without ever building per-doc dicts. Frames the mega transform
        # shrank rebuild their rows positionally from the plan
        # (synthesized zero-op quads interleaved with harvested rows).
        from ..protocol.codec import StormAck
        t_ack0 = _time.monotonic_ns()
        mega_plans = rec.get("mega_plans") or {}
        acks = []
        for ack_i, (frame, i0, i1) in enumerate(rec["acks"]):
            if frame.push is None:
                continue
            plan = mega_plans.get(ack_i)
            if plan is None:
                payload = StormAck(frame.rid, ack_rows[i0:i1])
            else:
                rows = np.empty((len(plan), 4), np.int32)
                for j, (kind, v) in enumerate(plan):
                    rows[j] = v if kind == "s" else ack_rows[v]
                payload = StormAck(frame.rid, rows)
            if any_bad and bad_rows[i0:i1].any():
                # The tick's sequencing is durable and correct (the
                # ticket is exact; the poison is in the served planes) —
                # the ack stands, but the client learns its doc is
                # frozen: further submits nack until readmission.
                payload["quarantined"] = [
                    rec["descs"][i][0] for i in range(i0, i1) if bad_l[i]]
                payload["retry_after_s"] = self.busy_retry_s
            acks.append((frame, payload))
        t_harvest_done = _time.monotonic_ns()
        stage_ns["ack_pack"] += t_harvest_done - t_ack0
        # Commit the tick's ledger record (fixed shape; replay ticks are
        # reconstruction, not serving — they don't pollute attribution).
        # Group-mode commit-wait is unknown until the fsync watermark
        # passes the tick; the drain backfills it on the record object.
        led = None
        if not self._replay:
            # The tick's exclusive wall-clock slice: harvest-to-harvest
            # cadence at steady state, its own stage span after an idle
            # gap (min of the two — an idle wait is not tick time).
            # Under pipelining the per-stage splits legitimately sum
            # PAST this wall slice; attribution() reports the difference
            # as overlap_ms instead of double-counting it.
            start_ns = rec.get("start_ns", t_harvest_done)
            wall_ns = t_harvest_done - start_ns
            if self._last_harvest_done_ns is not None:
                wall_ns = min(wall_ns,
                              t_harvest_done - self._last_harvest_done_ns)
            self._last_harvest_done_ns = t_harvest_done
            led = self.ledger.record(tick_id, rec.get("queue_depth", 0),
                                     len(rec["descs"]), rec["submitted"],
                                     stage_ns, wall_ns=max(0, wall_ns),
                                     depth=rec.get("depth",
                                                   self.pipeline_depth))
        if self._group_wal is not None and not self._replay:
            # Withhold until fsynced — then deliver in tick order with the
            # durability watermark stamped on (clients resubmit anything
            # above the watermark after a reconnect). The serial fallback
            # already measured its inline barrier as wal_commit_wait, so
            # its record must NOT be amended at drain (led=None there).
            self._unacked.append((tick_id, acks, t_harvest_done,
                                  led if self.pipeline_depth > 0
                                  else None))
            self._drain_durable_acks()
        else:
            dw = self.durable_watermark
            t_ack_tx = _time.monotonic_ns()
            for frame, payload in acks:
                faults.crashpoint("storm.pre_ack")
                payload["dw"] = dw
                if frame.trace is not None:
                    self._stamp_trace_ack(frame, payload)
                if frame.t0:
                    self.qos.observe_ack(frame.tenant,
                                         (t_ack_tx - frame.t0) / 1e9)
                frame.push(payload)

    # -- snapshot / recovery ---------------------------------------------------
    #
    # The crash-consistency pair (ISSUE 4 tentpole): checkpoint() writes a
    # device-pool snapshot (sequencer rows + merge-host pools + the WAL
    # tick watermark) to the content-addressed snapshot store; recover()
    # restores the head and replays the WAL tail THROUGH THE SERVING TICK
    # itself (same fused program, recorded timestamps), so a restarted
    # controller reconverges byte-identically with an uninterrupted twin.
    # tools/chaos.py kills the process at every dangerous point and
    # proves exactly that.

    SNAPSHOT_DOC = "__storm__"

    def checkpoint(self) -> str:
        """Settle everything (harvest + durability barrier), then publish
        one snapshot atomically: upload first, flip the head ref last —
        a crash mid-checkpoint leaves the previous head intact."""
        assert self.snapshots is not None, "no snapshot store attached"
        if self.replication is not None and self.replication.fenced:
            # A demoted leader's snapshot would clobber the promoted
            # incarnation's head — the zombie-writes hazard fencing
            # exists to stop.
            raise RuntimeError(
                "checkpoint() on a fenced (demoted) leader; the "
                f"promoted incarnation {self.replication.moved_to!r} "
                "owns the snapshot head")
        if self.wal_degraded:
            from .durable_store import WalDegradedError
            raise WalDegradedError(
                "checkpoint() while the WAL fsync breaker is open: the "
                "snapshot watermark cannot barrier on durability")
        if self.quarantined:
            # A snapshot taken now would capture the quarantined docs'
            # POISONED device rows — and readmit_doc rebuilds from the
            # snapshot head, so the poison would become the rebuild
            # source and the freeze unliftable. Readmit first.
            raise RuntimeError(
                f"checkpoint() with quarantined docs "
                f"{sorted(self.quarantined)}: readmit them first (a "
                "snapshot would capture their poisoned rows)")
        self._in_checkpoint = True
        try:
            self.flush()
            if self.wal_degraded:
                # Re-check AFTER the flush: the breaker may have opened
                # during it (flush swallows the barrier failure to keep
                # serving) — publishing now would stamp a tick_watermark
                # the WAL never made durable.
                from .durable_store import WalDegradedError
                raise WalDegradedError(
                    "WAL fsync breaker opened during the checkpoint "
                    "flush; snapshot watermark would not be durable")
            if self.quarantined:
                # Same re-check for quarantine: the settle flush itself
                # may have tripped the sentinel, and the poisoned row
                # must never become a rebuild source.
                raise RuntimeError(
                    f"sentinel quarantined {sorted(self.quarantined)} "
                    "during the checkpoint flush; readmit before "
                    "snapshotting")
            import dataclasses
            snap = {
                "kind": "storm-checkpoint",
                "format_version": STORM_SNAPSHOT_VERSION,
                "tick_watermark": self._tick_counter,
                "sequencer": {
                    doc: dataclasses.asdict(cp)
                    for doc, cp in self.seq_host.checkpoint_all().items()},
                "merge_host": self.merge_host.export_state(),
            }
            if self.megadoc is not None and self.megadoc.docs:
                # Lane DEVICE rows already ride checkpoint_all (lane ids
                # are sequencer docs) and the merge-host export; this is
                # the combiner's host state (mirrors + combine logs).
                snap["megadoc"] = self.megadoc.export_state()
            if not self.qos.is_trivial():
                # Fair-composition state (deficits + rotation): restored
                # at recover() and then rolled forward by the WAL tail's
                # per-tick "qos" headers — deficit counters survive
                # restarts exactly like the cohort machinery.
                snap["qos"] = self.qos.export_state()
            if self.history is not None and self.history.branches:
                # Branch registry (summaries and cold seeds are already
                # store-resident under their own heads).
                snap["history"] = self.history.export_state()
            handle = self.snapshots.upload(self.SNAPSHOT_DOC, snap)
            faults.crashpoint("snapshot.pre_publish")
            self.snapshots.set_head(self.SNAPSHOT_DOC, handle)
            self._last_checkpoint_tick = self._tick_counter
            if self.replication is not None:
                # Replica-side WAL retention: the snapshot watermark is
                # the followers' trim floor (recovery never replays
                # below it); the plane names the sub-floor ticks still
                # live here so follower reads stay byte-identical.
                self.replication.ship_retention(self._last_checkpoint_tick)
            return handle
        finally:
            self._in_checkpoint = False

    def recover(self) -> dict:
        """Restore the snapshot head (when one exists) into the sequencer
        and merge hosts, then replay the WAL ticks past the snapshot's
        watermark. Call once on a FRESH controller stack, before serving.
        Without a snapshot the durable tick history is still readable
        (the __init__ scan) but live state starts empty — the per-op tier
        then rebuilds from the bus/scriptorium replay instead."""
        assert not self._frames and not self._inflight, (
            "recover() on a controller already serving")
        restored_from = None
        start = 0
        if self.snapshots is not None:
            head = self.snapshots.head(self.SNAPSHOT_DOC)
            snap = self.snapshots.get(self.SNAPSHOT_DOC, head)
            if snap is not None:
                version = snap.get("format_version", 0)
                if not 0 <= version <= STORM_SNAPSHOT_VERSION:
                    raise ValueError(
                        f"storm snapshot format v{version} is newer than "
                        f"this reader (max v{STORM_SNAPSHOT_VERSION})")
                from .sequencer import SequencerCheckpoint
                for doc, cp in sorted(snap["sequencer"].items()):
                    self.seq_host.restore(doc, SequencerCheckpoint(**cp))
                self.merge_host.import_state(snap["merge_host"])
                if snap.get("megadoc") is not None:
                    if self.megadoc is None:
                        raise RuntimeError(
                            "snapshot holds mega-doc combiner state but "
                            "no MegaDocManager is attached")
                    self.megadoc.import_state(snap["megadoc"])
                if snap.get("qos") is not None:
                    self.qos.import_state(snap["qos"])
                if snap.get("history") is not None:
                    if self.history is None:
                        raise RuntimeError(
                            "snapshot holds history-plane branch state "
                            "but no HistoryPlane is attached")
                    self.history.import_state(snap["history"])
                start = snap["tick_watermark"]
                restored_from = head
                if self.residency is not None:
                    # Docs the global snapshot restored are resident;
                    # the WAL-tail replay below hydrates cold docs on
                    # first touch (prepare_replay).
                    self.residency.adopt_resident()
            elif self._blob_log is not None and len(self._blob_log) > 0:
                # The WAL holds durable ticks but no snapshot is
                # readable (corrupt head/chunks, or a crash before the
                # first checkpoint). Serving EMPTY live state over a
                # non-empty acked history would silently diverge from
                # what clients already saw — fail loudly instead; the
                # operator restores a snapshot or clears the spill dir.
                raise RuntimeError(
                    f"recover(): WAL holds {len(self._blob_log)} durable "
                    "ticks but no snapshot head is readable; refusing to "
                    "serve empty state over an acked history")
        # Memory-only serving with snapshots: tick ids continue past the
        # watermark (no blob scan set them), so fresh ticks never alias.
        self._tick_counter = max(self._tick_counter, start)
        durable = len(self._blob_log) if self._blob_log is not None else 0
        if self._blob_log is not None and start > durable:
            # Snapshot watermark ahead of the WAL: an unfsynced tail died
            # with the host (possible under durability != "group"; the
            # group mode's checkpoint barrier makes watermark <= durable).
            # The snapshot itself holds the full state at the watermark,
            # but tick ids must stay 1:1 with WAL record indices
            # (_read_blob), so realign by padding empty filler ticks —
            # they carry no docs, so no index or catch-up read ever
            # resolves into them.
            import json as _json
            import struct as _struct
            header = _json.dumps({"ts": 0, "docs": []},
                                 separators=(",", ":")).encode()
            filler = _struct.pack("<I", len(header)) + header
            while len(self._blob_log) < start:
                self._blob_log.append(filler)
            if self._group_wal is not None:
                self._group_wal.sync()
            durable = len(self._blob_log)
        replayed = 0
        if restored_from is not None and start < durable:
            replayed = self._replay_wal(start, durable)
        self._last_checkpoint_tick = self._tick_counter
        if self.residency is not None:
            # Trim the blob-scan index back to the hot set: cold docs'
            # indexes live in their cold snapshots (restored on hydrate).
            self.residency.after_recover()
        return {"restored_from": restored_from, "replayed_ticks": replayed}

    def _replay_wal(self, start: int, end: int) -> int:
        """Re-run ticks [start, end) from their durable blobs through the
        serving path: same cohorts, same recorded timestamps, no
        re-persisting (the blob being replayed IS the durable record)."""
        self._replay = True
        try:
            for tick in range(start, end):
                blob = self._read_blob(tick)
                header, off = self._parse_header(blob)
                if header.get("qos") is not None:
                    # Roll the scheduler forward to the state this tick
                    # was composed against (composition itself is NOT
                    # re-run — the recorded cohort IS the composition).
                    self.qos.import_state(header["qos"])
                mg = header.get("mg")
                if mg is not None:
                    # Mega-doc lifecycle control record: re-apply the
                    # event at the identical point in the total order
                    # (promotion re-seeds from the recovered checkpoint,
                    # demotion re-folds the recovered lanes).
                    if self.megadoc is None:
                        raise RuntimeError(
                            "WAL holds mega-doc control records but no "
                            "MegaDocManager is attached — attach one "
                            "before recover()")
                    self._tick_counter = tick + 1
                    self.megadoc.apply_control(mg, header["ts"])
                    continue
                hp = header.get("hp")
                if hp is not None:
                    # History-plane control record: branch forks re-seed
                    # at the identical point in the total order (the
                    # seed is a pure function of the records below this
                    # tick); trimmed-tick fillers are stateless.
                    self._tick_counter = tick + 1
                    if hp.get("op") == "trimmed" or hp.get("trimmed"):
                        continue
                    if self.history is None:
                        raise RuntimeError(
                            "WAL holds history-plane control records "
                            "but no HistoryPlane is attached — attach "
                            "one before recover()")
                    self.history.apply_control(hp, header["ts"])
                    continue
                self._tick_counter = tick
                self._replay_ts = header["ts"]
                entries = [e[:5] for e in header["docs"]]
                payload = memoryview(blob)[off:]
                if self.residency is not None:
                    # Hydrate cold docs on first touch; drop the entries
                    # a doc's cold snapshot already reflects (ticks
                    # below its watermark) — watermark-exact, per-doc
                    # independent, so peers replay unchanged.
                    kept = self.residency.prepare_replay(entries, tick)
                    if not kept:
                        # Whole tick inside cold snapshots: account for
                        # it without a device tick (ids must stay 1:1
                        # with WAL record indices).
                        self._tick_counter = tick + 1
                        continue
                    if len(kept) != len(entries):
                        # The payload is positional (words located by
                        # cumulative counts), so dropped entries splice
                        # their word slices out too — each header entry
                        # records its byte offset (index 9).
                        w_off = {e[0]: e[9] for e in header["docs"]}
                        payload = memoryview(b"".join(
                            bytes(payload[w_off[doc]:
                                          w_off[doc] + count * 4])
                            for doc, _c, _c0, _r, count in kept))
                    entries = kept
                self._adopt_replay_clients(entries, header)
                self.submit_frame(None, {"docs": entries, "rid": None},
                                  payload)
                self.flush()
        finally:
            self._replay = False
            self._replay_ts = None
        assert self._tick_counter == end, (self._tick_counter, end)
        return end - start

    def _adopt_replay_clients(self, entries: list, header: dict) -> None:
        """A client named by a durable tick header that the restored
        row does not know joined AFTER the restore source (a branch
        fork seed, or a fresh doc created past the last checkpoint —
        membership rides the bus tier, never the storm WAL). Replaying
        its frame against the ghost lane would silently drop ops the
        live tick acked, so adopt the client at its RECORDED dedup
        prefix: ``cseq`` just below the first sequenced op (the header's
        ``count - ns`` dup prefix replays to the identical outcome) and
        ``cref`` at the entry's ref (what the live tick left behind).
        Mega lane ids are skipped — the combiner mirror syncs lane
        membership itself (replay_decide)."""
        rec_by_doc = {e[0]: e for e in header["docs"]}
        for doc, client, cseq0, ref, count in entries:
            if self.megadoc is not None \
                    and self.megadoc.parent_of(doc) is not None:
                continue
            row = self.seq_host._rows.get(doc)
            if row is not None and client in self.seq_host._slots[row]:
                continue
            ns = rec_by_doc[doc][5]
            self.seq_host._row(doc)
            cp = self.seq_host.checkpoint(doc)
            cp.clients.append({
                "client_id": client,
                "client_seq": cseq0 + (count - ns) - 1,
                "ref_seq": ref,
                "last_update": header["ts"],
                "can_evict": True, "can_summarize": True,
                "nack": False,
            })
            self.seq_host.restore(doc, cp)

    # -- per-doc quarantine ----------------------------------------------------
    #
    # The blast-radius tentpole: one poisoned document must never take
    # its batch down. Detection is the device sentinel in _storm_tick
    # (vseq drift / negative planes); _quarantine_doc freezes ONLY the
    # flagged doc — buffered frames touching it nack retryable, new
    # submits shed at _admit, reads serve through the scalar fold of the
    # durable records — and readmit_doc() rebuilds it from the snapshot
    # head + its own WAL tail (exact: storm tickets are per-doc
    # independent) while every other row keeps full-rate serving.

    def _quarantine_doc(self, doc_id: str, reason: str,
                        tick_id: int) -> None:
        self.quarantined[doc_id] = {"reason": reason, "tick": tick_id}
        if self.megadoc is not None:
            # A poisoned LANE freezes the whole promoted doc: submits
            # name the parent (admission checks run pre-rewrite), and a
            # partial freeze would let sibling lanes advance the doc's
            # total order past an unservable range. Readmission of a
            # promoted doc is demote-after-readmit (see module doc).
            parent = self.megadoc.parent_of(doc_id)
            if parent is not None:
                for other in [parent] + self.megadoc.lane_ids(parent):
                    if other not in self.quarantined:
                        self._quarantine_doc(other, reason, tick_id)
        self.stats["quarantined_docs"] += 1
        self.merge_host.metrics.counter("storm.quarantines").inc()
        # In-flight ops: nack every BUFFERED frame touching the doc with
        # a retryable code (the client resubmits after readmission; cseq
        # dedup absorbs any overlap). Frames NOT touching the doc stay
        # queued; a frame sharing it is dropped whole (acks are
        # positional per frame) with every dropped doc listed, so the
        # client resubmits its healthy docs immediately.
        kept: list[_Frame] = []
        for frame in self._frames:
            if not any(d == doc_id for d, *_ in frame.docs):
                kept.append(frame)
                continue
            self._pending_docs -= len(frame.docs)
            # Refund the shed frame's staged ledger ns and trace slot:
            # a tick that never served it must not inherit its
            # attribution, and its sampling-cap slot frees for peers.
            self._staged_ns["ingress_decode"] -= frame.staged_ns[0]
            self._staged_ns["admission"] -= frame.staged_ns[1]
            if frame.trace is not None:
                self._traced_pending = max(0, self._traced_pending - 1)
            self._shed(frame.push, {"rid": frame.rid},
                       sum(n for *_, n in frame.docs), "quarantined",
                       self.busy_retry_s,
                       docs=[d for d, *_ in frame.docs],
                       quarantined=[doc_id], tenant=frame.tenant)
        self._frames = kept
        self.qos.reset_pending(self._frames)

    def quarantined_map_entries(self, doc_id: str) -> dict:
        """Scalar-engine serving for a quarantined doc: fold the durable
        columnar records (poison-free by construction — the ticket plane
        is exact even when the served planes corrupt) into the converged
        map. The doc stays readable at scalar cost while frozen."""
        from ..dds.map_data import MapData
        if self.history is not None and self.history.tail_floor(doc_id):
            # A compacted+trimmed doc's record prefix is gone — the
            # summary chain is the authoritative base; the history fold
            # serves the same converged entries shape.
            return self.history.read_at(
                doc_id, self.history.head_seq(doc_id))["entries"]
        records = self.records_overlapping(doc_id, 0)
        data = MapData()
        for m in materialize_storm_records(records, self.datastore,
                                           self.channel,
                                           blob_reader=self.read_tick_words):
            data.process(m.contents["contents"]["contents"], False, None)
        return dict(data.items())

    def readmit_doc(self, doc_id: str, verify: bool = True) -> dict:
        """From-snapshot rebuild of ONE quarantined document: restore its
        sequencer row and map row from the snapshot head, replay its WAL
        tail through the serving tick (recorded timestamps, single-doc
        cohorts), verify against the scalar fold, and lift the freeze.
        The rest of the batch serves normally throughout."""
        assert doc_id in self.quarantined, f"{doc_id!r} not quarantined"
        assert self.snapshots is not None, \
            "readmit_doc needs a snapshot store"
        self.flush()  # settle peers; the doc itself has nothing buffered
        head = self.snapshots.head(self.SNAPSHOT_DOC)
        snap = self.snapshots.get(self.SNAPSHOT_DOC, head)
        assert snap is not None, "no readable snapshot head to rebuild from"
        from .sequencer import SequencerCheckpoint
        cp = snap["sequencer"].get(doc_id)
        assert cp is not None, f"snapshot holds no sequencer row for {doc_id}"
        self.seq_host.restore(doc_id, SequencerCheckpoint(**cp))
        self._restore_map_row(doc_id, snap["merge_host"])
        start = snap["tick_watermark"]
        end = saved_counter = self._tick_counter
        replayed = 0
        self._replay = True
        try:
            for tick in range(start, end):
                blob = self._read_blob(tick)
                header, off = self._parse_header(blob)
                for entry in header["docs"]:
                    doc, client, cseq0, ref, count = entry[:5]
                    if doc != doc_id or count <= 0:
                        continue
                    w_off = entry[9]
                    self._tick_counter = tick
                    self._replay_ts = header["ts"]
                    words = memoryview(blob)[off + w_off:
                                             off + w_off + count * 4]
                    self.submit_frame(
                        None, {"docs": [[doc, client, cseq0, ref, count]],
                               "rid": None}, words)
                    self.flush()
                    replayed += 1
                    break
        finally:
            self._replay = False
            self._replay_ts = None
            self._tick_counter = saved_counter
        if verify:
            rebuilt = self.merge_host.map_entries(doc_id, self.datastore,
                                                  self.channel)
            shadow = self.quarantined_map_entries(doc_id)
            assert rebuilt == shadow, (
                f"readmit of {doc_id!r} diverged from the durable-record "
                f"fold: {rebuilt} != {shadow}")
        info = self.quarantined.pop(doc_id)
        self.stats["readmitted_docs"] += 1
        self.merge_host.metrics.counter("storm.readmits").inc()
        return {"doc": doc_id, "reason": info["reason"],
                "replayed_ticks": replayed, "snapshot": head}

    def _restore_map_row(self, doc_id: str, host_snap: dict) -> None:
        """Overwrite the doc's LIVE device map row with its snapshot row
        (or init defaults when the snapshot predates the row) — the map
        half of the per-doc from-snapshot rebuild; peers' rows are
        untouched."""
        live_row = self._storm_map_row(doc_id)
        from .merge_host import _nd_unpack
        m = host_snap["map"]
        snap_row = None
        for rec in m["rows"]:
            if list(rec["key"]) == [doc_id, self.datastore, self.channel]:
                snap_row = rec["row"]
                break
        xs = self.merge_host._xstate
        s_live = xs.present.shape[1]
        vals = {"present": np.zeros(s_live, np.bool_),
                "value": np.zeros(s_live, np.int32),
                "vseq": np.full(s_live, -1, np.int32),
                "cleared_seq": np.int32(-1)}
        if snap_row is not None:
            planes = {f: _nd_unpack(m["planes"][f])
                      for f in mk.MapState._fields}
            s_snap = planes["present"].shape[1]
            assert s_snap <= s_live, (
                f"snapshot map row wider than live ({s_snap} > {s_live})")
            for f in ("present", "value", "vseq"):
                vals[f][:s_snap] = planes[f][snap_row]
            vals["cleared_seq"] = planes["cleared_seq"][snap_row]
        self.merge_host._xstate = mk.MapState(
            **{f: getattr(xs, f).at[live_row].set(vals[f])
               for f in mk.MapState._fields})

    @staticmethod
    def _parse_header(blob: bytes) -> tuple[dict, int]:
        """(header, words byte offset) — no copy of the words region.
        Validates the tick format version: headers without "v" are the
        committed pre-version (v0) format and parse identically; a
        version NEWER than this reader refuses loudly (a rolled-back
        binary must not misparse a newer WAL)."""
        import json as _json
        import struct as _struct

        hlen = _struct.unpack_from("<I", blob)[0]
        header = _json.loads(blob[4:4 + hlen].decode())
        version = header.get("v", 0)
        if not 0 <= version <= STORM_WAL_VERSION:
            raise ValueError(
                f"storm WAL tick format v{version} is newer than this "
                f"reader (max v{STORM_WAL_VERSION})")
        return header, 4 + hlen

    def _read_blob(self, tick_id: int) -> bytes:
        if self._blob_log is not None:
            if (self._group_wal is not None
                    and tick_id >= self._group_wal.durable_len):
                # Catch-up reads ARE durability proof to clients (the
                # DeltaManager watermark contract): a record must never
                # leave this process ahead of its fsync, so reading an
                # in-flight tick barriers the group commit first. Rare
                # (tip readers racing the writer thread) and bounded by
                # one group-commit latency. With the fsync breaker OPEN
                # this raises WalDegradedError rather than waiting out
                # the outage OR serving unfsynced bytes as durable —
                # reads below the watermark keep serving; tip reads fail
                # retryably (the front door answers the request with an
                # error and keeps the socket).
                self._group_wal.sync()
            return bytes(self._blob_log.read(tick_id))
        return self._tick_blobs[tick_id]

    def read_tick_words(self, tick_id: int) -> bytes:
        """Raw words of one harvested tick (scriptorium read path)."""
        blob = self._read_blob(tick_id)
        _header, off = self._parse_header(blob)
        return blob[off:]

    def trim_tick_blobs(self, ticks: set[int]) -> int:
        """Rewrite superseded tick blobs to tiny filler records (the
        history-plane tail trim): tick ids stay 1:1 with WAL positions
        — only the bytes shrink. Callers (HistoryPlane.trim_now) have
        already proven the ticks are below the checkpoint watermark
        (never replayed) and referenced by no live catch-up index. The
        filler still parses as a valid docs-less tick header, so a
        reused spill dir rescans cleanly."""
        if not ticks:
            return 0
        import json as _json
        import struct as _struct
        header = _json.dumps(
            {"v": STORM_WAL_VERSION, "ts": 0, "docs": [],
             "hp": {"op": "trimmed"}}, separators=(",", ":")).encode()
        filler = _struct.pack("<I", len(header)) + header

        def transform(idx: int, data: bytes) -> bytes | None:
            if idx in ticks and len(data) > len(filler):
                return filler
            return None

        if self._group_wal is not None:
            return self._group_wal.rewrite_records(transform)
        if self._blob_log is not None:
            # Plain OpLog spill ("sync"/"none"): the shared atomic
            # rewrite, no writer thread to coordinate with.
            from .durable_store import rewrite_oplog_records
            self._blob_log, changed = rewrite_oplog_records(
                self._blob_log, self._spill_path, transform)
            return changed
        changed = 0
        for tick in ticks:
            blob = self._tick_blobs.get(tick)
            if blob is not None and len(blob) > len(filler):
                self._tick_blobs[tick] = filler
                changed += 1
        return changed

    def records_overlapping(self, doc_id: str, from_seq: int,
                            to_seq: int | None = None) -> list[dict]:
        """Columnar scriptorium records of ``doc_id`` whose seq windows
        overlap (from_seq, to_seq] — resolved from the per-tick blobs via
        the compact in-RAM (first, last, tick) index. The shape matches
        what :func:`materialize_storm_records` consumes. A doc with
        mega-lane history merges its lane records translated to doc seq
        space through the combine logs."""
        if self.megadoc is not None and self.megadoc.has_history(doc_id):
            return self.megadoc.records(doc_id, from_seq, to_seq,
                                        self._records_for)
        return self._records_for(doc_id, from_seq, to_seq)

    def _records_for(self, doc_id: str, from_seq: int,
                     to_seq: int | None = None) -> list[dict]:
        """Untranslated per-id record resolution (lane ids included)."""
        out = []
        ticks = self._doc_ticks.get(doc_id)
        if ticks is None and self.residency is not None \
                and not self.residency.is_resident(doc_id):
            # Cold doc: its catch-up index rode the eviction snapshot.
            # A gap fetch is a READ — serve it from the cold head
            # without hydrating (readers must not churn the pool).
            ticks = self.residency.cold_doc_ticks(doc_id)
        for fs, ls, tick in ticks or ():
            if ls <= from_seq or (to_seq is not None and fs > to_seq):
                continue
            header, _off = self._parse_header(self._read_blob(tick))
            for (doc, client, cseq0, ref, count,
                 ns, hfs, hls, m, w_off) in header["docs"]:
                if doc == doc_id:
                    out.append({
                        "client": client, "first_cseq": cseq0,
                        "ref_seq": ref, "count": count, "n_seq": ns,
                        "first_seq": hfs, "last_seq": hls, "msn": m,
                        "timestamp": header["ts"], "tick": tick,
                        "w_off": w_off,
                    })
                    break
        return out

    def _storm_mrow(self, doc_id: str):
        """The doc's map-row OBJECT (cohort resolution caches it so the
        harvest's last_seq updates never re-key the row dict per doc)."""
        key = ChannelKey(doc_id, self.datastore, self.channel)
        mrow = self.merge_host._map_rows.get(key)
        if mrow is None:
            mrow = self.merge_host._map_row(key)
            mrow.literal_values = True
            # Storm words address keys BY SLOT; pin the canonical names so
            # map_entries/materialization agree (10-bit slot space).
            mrow.key_slots = {f"k{s}": s
                              for s in range(self.merge_host._map_slots)}
        elif not getattr(mrow, "literal_values", False):
            raise ValueError(
                f"channel {key} already serves dict-path ops; storm and "
                "dict traffic cannot mix on one channel")
        return mrow

    def _storm_map_row(self, doc_id: str):
        return self._storm_mrow(doc_id).row


def materialize_storm_records(records: list[dict], datastore: str,
                              channel: str,
                              blob_reader=None
                              ) -> list[SequencedDocumentMessage]:
    """Per-op messages for catch-up readers (the lazy read path of the
    columnar scriptorium records). NACKed/IGNORED ops are omitted — only
    sequenced ops exist in the document's history.

    Records either embed their words (legacy ``"words"`` b64) or
    reference a per-tick blob (``"tick"`` + ``"w_off"`` — the harvest
    writes ONE raw blob per tick); pass the controller's
    :meth:`StormController.read_tick_words` as ``blob_reader`` to
    resolve the latter. Blobs are cached for the duration of the call.

    NOTE: a tick whose ops were partially rejected materializes its
    sequenced ops with consecutive seqs from first_seq (exact when
    rejections are a prefix — the common dup-resend shape)."""
    out: list[SequencedDocumentMessage] = []
    blob_cache: dict[int, bytes] = {}
    for rec in records:
        if rec["n_seq"] <= 0:
            continue
        if "words" in rec:
            words = np.frombuffer(base64.b64decode(rec["words"]),
                                  np.uint32, rec["count"])
        else:
            tick = rec["tick"]
            blob = blob_cache.get(tick)
            if blob is None:
                assert blob_reader is not None, (
                    "tick-blob record needs a blob_reader")
                blob = blob_reader(tick)
                blob_cache[tick] = blob
            words = np.frombuffer(blob, np.uint32, rec["count"],
                                  rec["w_off"])
        skip = rec["count"] - rec["n_seq"]  # rejected prefix (dup resend)
        for j in range(rec["n_seq"]):
            word = int(words[skip + j])
            kind = word & 3
            slot = (word >> 2) & 0x3FF
            value = (word >> 12) & 0xFFFFF
            if kind == mk.MAP_SET:
                contents = {"type": "set", "key": f"k{slot}",
                            "value": value}
            elif kind == mk.MAP_DELETE:
                contents = {"type": "delete", "key": f"k{slot}"}
            else:
                contents = {"type": "clear"}
            out.append(SequencedDocumentMessage(
                client_id=rec["client"],
                sequence_number=rec["first_seq"] + j,
                minimum_sequence_number=rec["msn"],
                client_sequence_number=rec["first_cseq"] + skip + j,
                reference_sequence_number=rec["ref_seq"],
                type=MessageType.OPERATION,
                contents={"address": datastore,
                          "contents": {"address": channel,
                                       "contents": contents}},
                timestamp=rec["timestamp"],
                data=None,
            ))
    return out


__all__ = ["StormController", "materialize_storm_records"]
