"""Multi-tenant QoS — deficit-weighted fair tick composition and
per-tenant SLO accounting (ROADMAP item 5, the round-17 tentpole).

Reference parity: the reference serves thousands of tenants through one
ordering service (riddler tenant/auth + alfred connect), but its
fairness story stops at admission throttles. Ours did too: PR 8's
per-tenant token buckets bound each tenant's admitted RATE, yet tick
batch composition stayed first-come — one hot tenant could fill every
tick's doc slots and move every other tenant's ack p99 (the classic
noisy-neighbor failure). This module adds the missing layer between
admission and the device tick:

* **per-tenant pending queues** — buffered storm frames group by the
  session-validated ``tenant_id`` (threaded through
  ``storm.submit_frame``; never the client-controlled frame header);
* **deficit round robin** — each tick, every tenant with pending
  frames accrues ``quantum x weight`` doc-slot credit (capped at one
  tick's quantum — an idle tick must not bank unbounded burst) and the
  composer drains frames in rotation while credit and the tick's slot
  budget last. An abusive tenant at 10x its rate saturates only its own
  share; the others' frames keep landing in the next tick;
* **weighted shed** — under queue pressure the OVER-share tenant sheds
  first (per-tenant pending caps; borrowing beyond the weighted share
  is allowed only while the global queue is shallow), and busy-nacks
  carry a per-tenant ``retry_after_s`` scaled by that tenant's own
  backlog, so the abuser backs off hardest;
* **per-tenant observability** — sequenced/submitted/shed counters and
  an ack-latency histogram per tenant in the shared registry
  (``storm.tenant.<id>.*`` — alfred's ``get_metrics`` exports them,
  ``tools/monitor.py render_tenants`` renders the SLO columns), plus a
  bounded ring of per-tick slot slices for windowed share attribution.

Determinism and replay safety: composition is a pure function of
(scheduler state, buffered frames), scheduler state is tiny
(deficits + rotation), rides every tick's WAL header (``"qos"`` field)
and the storm snapshot, and ``StormController._replay_wal`` restores it
tick by tick — so a recovered host resumes composing exactly where the
crashed one stopped (chaos kill point ``storm.qos_mid_compose``).
A single-tenant scheduler with no slot budget composes EXACTLY the
legacy first-come cohort (the compatibility bar every pre-QoS test
holds us to).
"""

from __future__ import annotations

import math
from collections import deque
from typing import Any

#: Tenant every unauthenticated session lands on (riddler-less doors).
DEFAULT_TENANT = "default"


class TenantScheduler:
    """Deficit-round-robin composer + per-tenant QoS bookkeeping.

    ``weights`` maps tenant id -> relative share (default 1.0 each).
    ``quantum_docs`` is the per-tick credit a weight-1.0 tenant accrues;
    None derives it from the tick slot budget at compose time (budget /
    total active weight — the work-conserving default).

    The scheduler never owns frames: :meth:`compose` PLANS a tick over
    the controller's buffered frame list (collision/fence rules
    included) and :meth:`commit` applies the plan's deficit charges —
    split so an undersized cohort can be declined without moving
    scheduler state (the ``require_full`` contract).
    """

    def __init__(self, weights: dict[str, float] | None = None,
                 default_weight: float = 1.0,
                 quantum_docs: int | None = None,
                 weight_source=None,
                 registry=None, prefix: str = "storm.tenant",
                 slice_capacity: int = 1024) -> None:
        self.weights: dict[str, float] = dict(weights or {})
        for t, w in self.weights.items():
            if w <= 0:
                raise ValueError(f"tenant {t!r} weight must be > 0, got {w}")
        # Tenant-record weight derivation (riddler paid tiers): a
        # callable ``tenant_id -> weight | None`` consulted LIVE for
        # tenants with no explicit/journaled weight — never cached, so
        # a ``set_tier`` upgrade takes effect on the very next compose
        # and an idle tenant's derived weight never bloats the
        # journaled roster (pending_cap counts configured tenants as
        # active). Recovery re-derives from the same durable tenant
        # store; replay itself never re-composes, so weights need no
        # per-tick journal of their own.
        self.weight_source = weight_source
        self.default_weight = float(default_weight)
        self.quantum_docs = quantum_docs
        self._registry = registry
        self._prefix = prefix
        # Runtime weight changes (set_weight / weight_source cache) must
        # journal even before multi-tenant traffic makes the deficits
        # non-trivial — constructor config alone stays unstamped (the
        # pre-QoS byte-compat contract).
        self._weights_dirty = False
        # DRR state (the replay-safe part): per-tenant deficit credit +
        # the rotation order/pointer. Rotation entry is first-seen order
        # — deterministic under deterministic workloads.
        self.deficit: dict[str, float] = {}
        self._rr: list[str] = []
        self._rr_idx = 0
        # Live accounting (NOT replayed — rebuilt from buffered frames).
        self.pending_docs: dict[str, int] = {}
        # doc -> owning tenant (observed at submit; bounded, insertion-
        # ordered eviction). The cluster placement tier reads this to
        # spread a hot tenant's docs ACROSS hosts instead of letting it
        # saturate its weighted share on one (parallel/placement.py).
        self.doc_tenant: dict[str, str] = {}
        self.max_doc_tenants = 65536
        # Windowed per-tick slot slices: (tick, {tenant: [docs, ops]}).
        self._slices: deque = deque(maxlen=max(1, slice_capacity))
        # Lazily-created per-tenant metrics (a tenant that never sends
        # never appears in a scrape).
        self._counters: dict[tuple[str, str], Any] = {}
        self._hists: dict[str, Any] = {}
        self._gauges: dict[str, Any] = {}

    # -- weights ---------------------------------------------------------------

    def weight(self, tenant: str) -> float:
        w = self.weights.get(tenant)
        if w is not None:
            return w
        if self.weight_source is not None:
            derived = self.weight_source(tenant)
            if derived is not None and derived > 0:
                return float(derived)
        return self.default_weight

    def set_weight(self, tenant: str, weight: float) -> None:
        """Runtime weight change — journals like scheduler state: the
        next composed tick's WAL header (and the next snapshot) carries
        it, and recovery restores it (import_state OVERRIDES, so a
        journaled change survives a restart over static config)."""
        if weight <= 0:
            raise ValueError(f"weight must be > 0, got {weight}")
        self.weights[tenant] = float(weight)
        self._weights_dirty = True

    # -- metrics plumbing ------------------------------------------------------

    def _counter(self, tenant: str, name: str):
        key = (tenant, name)
        c = self._counters.get(key)
        if c is None and self._registry is not None:
            c = self._registry.counter(f"{self._prefix}.{tenant}.{name}")
            self._counters[key] = c
        return c

    def _hist(self, tenant: str):
        h = self._hists.get(tenant)
        if h is None and self._registry is not None:
            h = self._registry.histogram(f"{self._prefix}.{tenant}.ack_s")
            self._hists[tenant] = h
        return h

    def _gauge(self, tenant: str):
        g = self._gauges.get(tenant)
        if g is None and self._registry is not None:
            g = self._registry.gauge(
                f"{self._prefix}.{tenant}.pending_docs")
            self._gauges[tenant] = g
        return g

    # -- live accounting -------------------------------------------------------

    def note_submitted(self, tenant: str, n_ops: int) -> None:
        c = self._counter(tenant, "submitted_ops")
        if c is not None:
            c.inc(n_ops)

    def note_buffered(self, tenant: str, n_docs: int) -> None:
        self.pending_docs[tenant] = self.pending_docs.get(tenant, 0) \
            + n_docs
        g = self._gauge(tenant)
        if g is not None:
            g.set(self.pending_docs[tenant])

    def note_doc_tenants(self, tenant: str, docs) -> None:
        """Record doc ownership for the placement tier (called per
        multi-tenant frame; re-insertion refreshes the eviction order)."""
        dt = self.doc_tenant
        for doc in docs:
            dt.pop(doc, None)
            dt[doc] = tenant
        while len(dt) > self.max_doc_tenants:
            dt.pop(next(iter(dt)))

    def note_shed(self, tenant: str, n_ops: int) -> None:
        c = self._counter(tenant, "shed_ops")
        if c is not None:
            c.inc(n_ops)

    def observe_ack(self, tenant: str, latency_s: float) -> None:
        h = self._hist(tenant)
        if h is not None:
            h.observe(max(0.0, latency_s))

    def reset_pending(self, frames) -> None:
        """Rebuild the per-tenant pending-doc levels from the
        controller's buffered frame list (called once per composed tick
        — the buffered set is bounded by ``max_pending_docs``)."""
        fresh: dict[str, int] = {}
        for f in frames:
            t = getattr(f, "tenant", DEFAULT_TENANT)
            fresh[t] = fresh.get(t, 0) + len(f.docs)
        for t in set(self.pending_docs) | set(fresh):
            level = fresh.get(t, 0)
            self.pending_docs[t] = level
            g = self._gauge(t)
            if g is not None:
                g.set(level)

    # -- weighted shed (the _admit seam) ---------------------------------------

    def pending_cap(self, tenant: str, max_pending: int) -> int | None:
        """This tenant's weighted share of the bounded inbound queue, or
        None when only one tenant is in play (single-tenant serving must
        keep the legacy global bound exactly)."""
        active = {t for t, n in self.pending_docs.items() if n > 0}
        active.add(tenant)
        active.update(self.weights)
        if len(active) <= 1:
            return None
        total_w = sum(self.weight(t) for t in active)
        return max(1, int(max_pending * self.weight(tenant) / total_w))

    def shed_hint(self, tenant: str, base_s: float,
                  max_pending: int | None = None) -> float:
        """Per-tenant ``retry_after_s``: the deeper THIS tenant's own
        backlog relative to its share, the longer it is told to wait —
        the abuser backs off hardest while a victim tenant's hint stays
        at the base."""
        if max_pending is None:
            return base_s
        cap = self.pending_cap(tenant, max_pending)
        if cap is None:
            return base_s
        backlog = self.pending_docs.get(tenant, 0)
        return base_s * (1.0 + backlog / cap)

    # -- composition (the tick seam) -------------------------------------------

    def _cap_for(self, tenant: str, quantum: float) -> float:
        return max(1.0, quantum * self.weight(tenant))

    def compose(self, frames: list, budget: int | None = None) -> dict:
        """Plan one tick's cohort over the buffered ``frames`` (arrival
        order). Returns a plan dict::

            {"selected": [frame, ...],   # in serving order
             "kept": [frame, ...],       # arrival order, unselected
             "charge": {tenant: docs},   # deficit debits commit() applies
             "slices": {tenant: docs}}   # per-tenant slots this tick

        Rules, in priority order: (1) one frame per doc per tick — a
        frame naming an already-taken doc is passed over (per-tenant
        FIFO holds; the frame stays buffered); (2) the mega FIFO fence —
        once any frame of a promoted doc is passed over, every later
        frame of that doc is too; (3) deficit round robin over tenants
        with ``budget`` total doc slots (None = unbounded). A
        single-tenant, unbounded compose reduces exactly to the legacy
        first-come scan. The plan is side-effect free until
        :meth:`commit` — scheduler state never moves for a declined
        cohort."""
        queues: dict[str, list] = {}
        for i, f in enumerate(frames):
            t = getattr(f, "tenant", DEFAULT_TENANT)
            queues.setdefault(t, []).append((i, f))
        for t in queues:
            if t not in self.deficit:
                self.deficit[t] = 0.0
                self._rr.append(t)
        active = [t for t in self._rr if t in queues]
        remaining = math.inf if budget is None else max(1, int(budget))
        taken: set[str] = set()
        blocked_parents: set[str] = set()
        picked: list[tuple[int, Any]] = []
        charge: dict[str, float] = {}
        kept_idx: set[int] = set()
        plan_quantum: float | None = None

        def fdocs(frame) -> set[str]:
            return {doc for doc, *_ in frame.docs}

        def fparents(frame) -> set[str]:
            if frame.mega is None:
                return set()
            return {info["doc"] for info in frame.mega if info is not None}

        # Global per-doc (and per-mega-parent) arrival heads: a frame is
        # takable only while it IS the oldest unselected frame naming
        # each of its docs — the rotation must never serve a later
        # arrival ahead of an earlier one for the SAME doc just because
        # they belong to different tenants (per-doc FIFO and the mega
        # cohort-admission-order law are cross-tenant invariants; the
        # per-tenant queues alone only guarantee them within a tenant).
        heads: dict[str, list[int]] = {}
        for i, f in enumerate(frames):
            for d in fdocs(f) | fparents(f):
                heads.setdefault(d, []).append(i)

        def try_take(i: int, frame, tenant: str) -> bool:
            """Collision/fence/arrival-order check + selection
            bookkeeping (shared by the fair and legacy paths)."""
            nonlocal remaining
            docs = fdocs(frame)
            parents = fparents(frame)
            stale = any(heads[d][0] != i for d in docs | parents)
            if stale or not taken.isdisjoint(docs) \
                    or not blocked_parents.isdisjoint(parents):
                blocked_parents.update(parents)
                kept_idx.add(i)
                return False
            for d in docs | parents:
                heads[d].pop(0)
            taken.update(docs)
            picked.append((i, frame))
            charge[tenant] = charge.get(tenant, 0.0) + len(frame.docs)
            remaining -= len(frame.docs)
            return True

        if len(active) == 1 and budget is None:
            # Legacy single-tenant scan: every disjoint frame serves
            # this tick, arrival order, no deficit charges (fairness is
            # moot with one tenant — and the pre-QoS byte-for-byte
            # behavior is the compatibility contract).
            t = active[0]
            for i, frame in queues[t]:
                try_take(i, frame, t)
            charge.clear()
        elif active:
            quantum = self.quantum_docs
            if quantum is None:
                total_w = sum(self.weight(t) for t in active)
                quantum = (remaining / total_w
                           if budget is not None else 64.0)
            plan_quantum = float(quantum)
            # Plan against a COPY of the deficits (commit applies them).
            deficit = dict(self.deficit)
            for t in active:
                cap = self._cap_for(t, quantum)
                deficit[t] = min(deficit[t] + quantum * self.weight(t),
                                 cap)
            # Rotation starts at the persistent pointer so leftover
            # budget rotates across ticks instead of favoring the
            # first-seen tenant forever.
            start = self._rr_idx % max(1, len(self._rr))
            rotation = [t for t in self._rr[start:] + self._rr[:start]
                        if t in queues]
            cursors = {t: 0 for t in rotation}

            def drain(use_credit: bool) -> None:
                """Round-robin pass: one frame per tenant visit, looped
                until no tenant progresses or the budget is spent. With
                ``use_credit`` a tenant stops at its deficit; without it
                (the borrow phase) any frame within the remaining budget
                serves — still charged, so the borrower's deficit goes
                negative and repays out of its next quanta."""
                nonlocal remaining
                progress = True
                while progress and remaining > 0:
                    progress = False
                    for t in rotation:
                        if remaining <= 0:
                            break
                        q = queues[t]
                        cur = cursors[t]
                        while cur < len(q):
                            i, frame = q[cur]
                            if i in kept_idx:
                                cur += 1
                                continue
                            cost = len(frame.docs)
                            if cost > remaining or (
                                    use_credit and deficit[t]
                                    < min(cost, self._cap_for(t, quantum))
                                    - 1e-9):
                                break  # out of credit/budget this visit
                            if try_take(i, frame, t):
                                deficit[t] -= cost
                                cur += 1
                                progress = True
                                break  # one frame/visit: round robin
                            cur += 1  # collision: scan past, stays
                        cursors[t] = cur

            drain(use_credit=True)
            # Work-conserving borrow phase: every fair quantum is spent
            # but slots remain — per-tick utilization stays full while
            # long-run shares hold (the victims' frames were already
            # served in the credit phase above).
            drain(use_credit=False)
            if not picked and frames:
                # Starvation guard (the oversized-frame case): serve the
                # oldest buffered frame regardless of credit — the
                # deficit goes negative and self-heals at quantum/tick,
                # so long-run fairness holds while progress is
                # guaranteed (flush(force=True) must always terminate).
                i, frame = min((it for q in queues.values() for it in q),
                               key=lambda it: it[0])
                t = getattr(frame, "tenant", DEFAULT_TENANT)
                kept_idx.discard(i)
                taken.clear()
                blocked_parents.clear()
                try_take(i, frame, t)
        picked.sort(key=lambda it: it[0])
        selected = [f for _i, f in picked]
        sel_idx = {i for i, _f in picked}
        kept = [f for i, f in enumerate(frames) if i not in sel_idx]
        slices = {}
        for _i, f in picked:
            t = getattr(f, "tenant", DEFAULT_TENANT)
            slices[t] = slices.get(t, 0) + len(f.docs)
        return {"selected": selected, "kept": kept, "charge": charge,
                "quantum": plan_quantum, "slices": slices}

    def commit(self, plan: dict) -> None:
        """Apply one composed tick's deficit movement: active tenants
        accrue their quantum (capped), selected frames debit theirs.
        Matches the arithmetic :meth:`compose` planned with."""
        charge = plan["charge"]
        if not charge:
            return  # single-tenant legacy tick: no fairness state moves
        active = {getattr(f, "tenant", DEFAULT_TENANT)
                  for f in plan["selected"] + plan["kept"]}
        quantum = plan.get("quantum")
        if quantum is None:
            quantum = self.quantum_docs if self.quantum_docs is not None \
                else 64.0
        for t in active:
            cap = self._cap_for(t, quantum)
            self.deficit[t] = min(
                self.deficit.get(t, 0.0) + quantum * self.weight(t), cap)
        for t, cost in charge.items():
            self.deficit[t] = self.deficit.get(t, 0.0) - cost
        if self._rr:
            self._rr_idx = (self._rr_idx + 1) % len(self._rr)

    # -- per-tick slices (the ledger slice plane) ------------------------------

    def note_tick(self, tick_id: int, slices: dict[str, int],
                  sequenced: dict[str, int] | None = None) -> None:
        """Record one harvested tick's per-tenant doc slots (+ sequenced
        ops) into the windowed ring and the cumulative counters."""
        rec = {t: [int(n), int((sequenced or {}).get(t, 0))]
               for t, n in slices.items()}
        for t, extra in (sequenced or {}).items():
            if t not in rec:
                rec[t] = [0, int(extra)]
        self._slices.append((int(tick_id), rec))
        for t, (docs, ops) in rec.items():
            c = self._counter(t, "tick_docs")
            if c is not None:
                c.inc(docs)
            c = self._counter(t, "sequenced_ops")
            if c is not None:
                c.inc(ops)

    def attribution(self) -> dict:
        """Windowed per-tenant share of tick doc slots:
        {tenant: {"share", "docs", "ops", "ticks"}} + "_window". The
        stage-ledger slice by tenant — which tenant consumed the
        serving capacity the ledger attributes to stages."""
        out: dict[str, Any] = {}
        slices = list(self._slices)
        totals: dict[str, list[int]] = {}
        ticks_seen: dict[str, int] = {}
        grand = 0
        for _tick, rec in slices:
            for t, (docs, ops) in rec.items():
                tot = totals.setdefault(t, [0, 0])
                tot[0] += docs
                tot[1] += ops
                ticks_seen[t] = ticks_seen.get(t, 0) + 1
                grand += docs
        for t, (docs, ops) in sorted(totals.items()):
            out[t] = {"share": round(docs / grand, 4) if grand else 0.0,
                      "docs": docs, "ops": ops,
                      "ticks": ticks_seen.get(t, 0),
                      "pending": self.pending_docs.get(t, 0)}
        out["_window"] = {"ticks": len(slices), "docs": grand}
        return out

    # -- replay-safe state -----------------------------------------------------

    def is_trivial(self) -> bool:
        """True while no fairness state worth journaling exists: at most
        the default tenant has ever composed AND no runtime weight
        change happened. Keeps single-tenant WAL headers byte-compatible
        with every pre-QoS reader and golden."""
        if self._weights_dirty:
            return False
        return not self.deficit or self._rr == [DEFAULT_TENANT]

    def export_state(self) -> dict:
        """The replay-safe scheduler state (deficits + rotation) — rides
        every multi-tenant tick's WAL header and the storm snapshot.
        Deficits export at FULL float precision (JSON round-trips
        doubles exactly): a rounded export would re-compose differently
        after recovery than the live host at an epsilon boundary."""
        return {"deficit": {t: float(d)
                            for t, d in sorted(self.deficit.items())},
                "rr": list(self._rr), "rr_idx": self._rr_idx,
                "weights": {t: w for t, w in sorted(self.weights.items())}}

    def import_state(self, snap: dict) -> None:
        self.deficit = {t: float(d)
                        for t, d in snap.get("deficit", {}).items()}
        self._rr = list(snap.get("rr", ()))
        self._rr_idx = int(snap.get("rr_idx", 0))
        for t, w in snap.get("weights", {}).items():
            # Journaled weights OVERRIDE constructor config: a runtime
            # set_weight is scheduler STATE, and recovery must compose
            # against what the crashed host actually used — the tick
            # headers roll these forward exactly like the deficits.
            self.weights[t] = float(w)
        if snap.get("weights"):
            # Restored runtime weights must KEEP journaling: without
            # this, a single-tenant host whose deficits look trivial
            # again would stop stamping headers and a second restart
            # would silently revert to constructor config.
            self._weights_dirty = True


__all__ = ["TenantScheduler", "DEFAULT_TENANT"]
