"""Scalar per-document sequencer — the host-side oracle and control path.

Reference parity: the deli lambda's ticket state machine
(server/routerlicious/packages/lambdas/src/deli/lambda.ts:236-470) and
``ClientSequenceNumberManager`` (deli/clientSeqManager.ts). This is the exact
sequential semantics the batched kernel in
:mod:`fluidframework_tpu.ops.sequencer` must reproduce; differential tests
drive both with identical op streams.

Rules, in check order (mirroring ticket()):
  1. nack-future control state → NACK everything.
  2. clientSeqNum dup/gap per client: == expected → ok, > → NACK gap,
     < → silent drop.
  3. system join/leave: membership upsert/remove; duplicate → silent drop.
  4. client checks: unknown/nacked client → NACK; refSeq below MSN → NACK
     (and mark the client nacked at refSeq=MSN); summarize without scope
     → NACK.
  5. sequence-number rev: client ops rev unless NOOP; system ops rev unless
     NOOP/NO_CLIENT/CONTROL. refSeq==-1 (direct REST op) is revved to the
     assigned seq.
  6. MSN = min over active clients' refSeq; if no clients, MSN jumps to seq.
  7. no-op consolidation heuristics decide SEND_IMMEDIATE/LATER/NEVER and may
     rev a no-op after all to carry a fresh MSN.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from ..ops import opcodes as oc
from ..protocol.messages import MessageType

# Types only the service itself may inject into a document's stream.
_SERVICE_ONLY_TYPES = frozenset({
    MessageType.CLIENT_JOIN,
    MessageType.CLIENT_LEAVE,
    MessageType.NO_CLIENT,
    MessageType.CONTROL,
    MessageType.SUMMARY_ACK,
    MessageType.SUMMARY_NACK,
})


@dataclass(slots=True)
class ClientEntry:
    """Per-client sequencing state (reference IClientSequenceNumber)."""

    client_id: str
    client_seq: int
    ref_seq: int
    last_update: int
    can_evict: bool = True
    can_summarize: bool = True
    nack: bool = False


@dataclass(frozen=True, slots=True)
class RawOperation:
    """A raw (unsequenced) op as it arrives at the sequencer."""

    client_id: str | None  # None = system message (join/leave/control)
    type: MessageType
    client_seq: int = 0
    ref_seq: int = 0
    timestamp: int = 0
    contents: Any = None
    data: Any = None  # join: ClientEntry-like detail; leave: client_id
    # join-time flags (carried in data for the scalar path):
    can_summarize: bool = True
    can_evict: bool = True
    # Latency breadcrumbs riding the op (protocol.ts:53 ITrace); alfred
    # stamps submit, deli appends start/end (deli/lambda.ts:160).
    traces: tuple = ()


@dataclass(frozen=True, slots=True)
class Ticket:
    """Outcome of sequencing one raw op."""

    kind: int  # oc.OUT_*
    seq: int = -1
    msn: int = -1
    send: int = oc.SEND_IMMEDIATE
    nack_code: int = oc.NACK_NONE
    op: RawOperation | None = None


@dataclass(slots=True)
class SequencerCheckpoint:
    """Durable restart state (reference deli checkpointContext {seq,msn,clients})."""

    sequence_number: int
    minimum_sequence_number: int
    last_sent_msn: int
    no_active_clients: bool
    clients: list[dict]
    nack_future: bool = False
    client_timeout_ms: int = 5 * 60 * 1000
    log_offset: int = -1


class DocumentSequencer:
    """Scalar total-order sequencer for one document."""

    def __init__(
        self,
        sequence_number: int = 0,
        minimum_sequence_number: int = 0,
        client_timeout_ms: int = 5 * 60 * 1000,
    ) -> None:
        self.sequence_number = sequence_number
        self.minimum_sequence_number = minimum_sequence_number
        self.last_sent_msn = minimum_sequence_number
        self.no_active_clients = True
        self.nack_future = False
        self.client_timeout_ms = client_timeout_ms
        self.clients: dict[str, ClientEntry] = {}

    # -- membership helpers --------------------------------------------------

    def _upsert(
        self,
        client_id: str,
        client_seq: int,
        ref_seq: int,
        timestamp: int,
        can_summarize: bool = True,
        can_evict: bool = True,
        nack: bool = False,
    ) -> bool:
        """Returns True iff this is a new client (clientSeqManager.upsertClient)."""
        entry = self.clients.get(client_id)
        if entry is not None:
            entry.client_seq = client_seq
            entry.ref_seq = ref_seq
            entry.last_update = timestamp
            entry.nack = nack
            return False
        self.clients[client_id] = ClientEntry(
            client_id=client_id,
            client_seq=client_seq,
            ref_seq=ref_seq,
            last_update=timestamp,
            can_evict=can_evict,
            can_summarize=can_summarize,
            nack=nack,
        )
        return True

    def _min_ref_seq(self) -> int:
        if not self.clients:
            return -1
        return min(entry.ref_seq for entry in self.clients.values())

    def get_idle_client(self, now: int,
                        timeout_ms: int | None = None) -> str | None:
        """Oldest client idle past the timeout, if any (deli getIdleClient)."""
        timeout = (self.client_timeout_ms if timeout_ms is None
                   else timeout_ms)
        idle = [
            e for e in self.clients.values()
            if e.can_evict and now - e.last_update > timeout
        ]
        if not idle:
            return None
        return min(idle, key=lambda e: (e.last_update, e.client_id)).client_id

    # -- the ticket state machine -------------------------------------------

    def ticket(self, op: RawOperation) -> Ticket:
        if self.nack_future:
            return Ticket(
                kind=oc.OUT_NACK,
                seq=self.sequence_number,
                msn=self.minimum_sequence_number,
                nack_code=oc.NACK_FUTURE,
                op=op,
            )

        # Dup/gap detection on the per-client sequence number.
        if op.client_id is not None:
            entry = self.clients.get(op.client_id)
            if entry is not None:
                expected = entry.client_seq + 1
                if op.client_seq > expected:
                    return Ticket(
                        kind=oc.OUT_NACK,
                        seq=self.sequence_number,
                        msn=self.minimum_sequence_number,
                        nack_code=oc.NACK_GAP,
                        op=op,
                    )
                if op.client_seq < expected:
                    return Ticket(kind=oc.OUT_IGNORED, op=op)

        if op.client_id is None:
            if op.type == MessageType.CLIENT_LEAVE:
                if op.data not in self.clients:
                    return Ticket(kind=oc.OUT_IGNORED, op=op)
                del self.clients[op.data]
            elif op.type == MessageType.CLIENT_JOIN:
                # data carries the join detail (ClientDetail) or a bare id
                # (reference IClientJoin {clientId, detail}).
                join_id = getattr(op.data, "client_id", op.data)
                is_new = self._upsert(
                    join_id,
                    0,
                    self.minimum_sequence_number,
                    op.timestamp,
                    can_summarize=op.can_summarize,
                    can_evict=op.can_evict,
                )
                if not is_new:
                    return Ticket(kind=oc.OUT_IGNORED, op=op)
        else:
            # Service-only types are rejected from clients: CONTROL could set
            # nack_future (DoS), NO_CLIENT/JOIN/LEAVE forge membership, and
            # SUMMARY_ACK/NACK forge the summary protocol.
            if op.type in _SERVICE_ONLY_TYPES:
                return Ticket(
                    kind=oc.OUT_NACK,
                    seq=self.sequence_number,
                    msn=self.minimum_sequence_number,
                    nack_code=oc.NACK_INVALID_TYPE,
                    op=op,
                )
            entry = self.clients.get(op.client_id)
            if entry is None or entry.nack:
                return Ticket(
                    kind=oc.OUT_NACK,
                    seq=self.sequence_number,
                    msn=self.minimum_sequence_number,
                    nack_code=oc.NACK_NONEXISTENT_CLIENT,
                    op=op,
                )
            if op.ref_seq != -1 and op.ref_seq < self.minimum_sequence_number:
                self._upsert(
                    op.client_id,
                    op.client_seq,
                    self.minimum_sequence_number,
                    op.timestamp,
                    nack=True,
                )
                return Ticket(
                    kind=oc.OUT_NACK,
                    seq=self.sequence_number,
                    msn=self.minimum_sequence_number,
                    nack_code=oc.NACK_REFSEQ_BELOW_MSN,
                    op=op,
                )
            if op.type == MessageType.SUMMARIZE and not entry.can_summarize:
                return Ticket(
                    kind=oc.OUT_NACK,
                    seq=self.sequence_number,
                    msn=self.minimum_sequence_number,
                    nack_code=oc.NACK_NO_SUMMARY_SCOPE,
                    op=op,
                )

        # Sequence-number rev.
        sequence_number = self.sequence_number
        ref_seq = op.ref_seq
        if op.client_id is not None:
            if op.type != MessageType.NOOP:
                sequence_number = self._rev()
            if ref_seq == -1:
                ref_seq = sequence_number
            self._upsert(op.client_id, op.client_seq, ref_seq, op.timestamp)
        else:
            if op.type not in (
                MessageType.NOOP,
                MessageType.NO_CLIENT,
                MessageType.CONTROL,
            ):
                sequence_number = self._rev()

        # MSN update.
        msn = self._min_ref_seq()
        if msn == -1:
            self.minimum_sequence_number = sequence_number
            self.no_active_clients = True
        else:
            self.minimum_sequence_number = msn
            self.no_active_clients = False

        # Send heuristics (no-op consolidation, deli lambda.ts:375-447).
        send = oc.SEND_IMMEDIATE
        if op.type == MessageType.NOOP:
            if op.client_id is not None:
                if op.contents is None:
                    send = oc.SEND_LATER
                elif self.minimum_sequence_number <= self.last_sent_msn:
                    send = oc.SEND_LATER
                else:
                    sequence_number = self._rev()
            else:
                if self.minimum_sequence_number <= self.last_sent_msn:
                    send = oc.SEND_NEVER
                else:
                    sequence_number = self._rev()
        elif op.type == MessageType.NO_CLIENT:
            if self.no_active_clients:
                sequence_number = self._rev()
                self.minimum_sequence_number = sequence_number
            else:
                send = oc.SEND_NEVER
        elif op.type == MessageType.CONTROL:
            send = oc.SEND_NEVER
            if isinstance(op.contents, dict) and op.contents.get("type") == "nackFuture":
                self.nack_future = True

        if send == oc.SEND_IMMEDIATE:
            self.last_sent_msn = self.minimum_sequence_number

        return Ticket(
            kind=oc.OUT_SEQUENCED,
            seq=sequence_number,
            msn=self.minimum_sequence_number,
            send=send,
            op=op,
        )

    def _rev(self) -> int:
        self.sequence_number += 1
        return self.sequence_number

    # -- checkpoint/restore (deli checkpointContext.ts) ----------------------

    def checkpoint(self, log_offset: int = -1) -> SequencerCheckpoint:
        return SequencerCheckpoint(
            sequence_number=self.sequence_number,
            minimum_sequence_number=self.minimum_sequence_number,
            last_sent_msn=self.last_sent_msn,
            no_active_clients=self.no_active_clients,
            nack_future=self.nack_future,
            client_timeout_ms=self.client_timeout_ms,
            clients=[
                {
                    "client_id": e.client_id,
                    "client_seq": e.client_seq,
                    "ref_seq": e.ref_seq,
                    "last_update": e.last_update,
                    "can_evict": e.can_evict,
                    "can_summarize": e.can_summarize,
                    "nack": e.nack,
                }
                for e in sorted(self.clients.values(), key=lambda e: e.client_id)
            ],
            log_offset=log_offset,
        )

    @classmethod
    def restore(cls, cp: SequencerCheckpoint) -> "DocumentSequencer":
        seq = cls(
            sequence_number=cp.sequence_number,
            minimum_sequence_number=cp.minimum_sequence_number,
            client_timeout_ms=cp.client_timeout_ms,
        )
        seq.last_sent_msn = cp.last_sent_msn
        seq.no_active_clients = cp.no_active_clients
        seq.nack_future = cp.nack_future
        for c in cp.clients:
            seq.clients[c["client_id"]] = ClientEntry(
                client_id=c["client_id"],
                client_seq=c["client_seq"],
                ref_seq=c["ref_seq"],
                last_update=c["last_update"],
                can_evict=c["can_evict"],
                can_summarize=c["can_summarize"],
                nack=c["nack"],
            )
        return seq
